"""Reshard-on-restore: map a raw checkpoint tree onto a live template.

Before r18 a checkpoint could only restore into the exact run shape that
wrote it: same layer layout (scanned / unrolled / pipelined stage
count), and any mismatch was a named refusal pointing at the offline
``tools/convert_checkpoint.py``. That posture is wrong for an elastic
fleet — the whole point of restarting on whatever capacity survives a
preemption is that the surviving shape is *different* — so this module
runs the converter logic *inside* restore:

1. **Layout detection + conversion** — the raw (template-free) state
   tree's layer layout is detected (``parallel/stacking``) and, when it
   differs from the template's, converted in-process with the same
   ``convert_tree_layout`` core the offline tool uses. Bit-exact: the
   conversions are pure restacking reshapes.
2. **Placement** — the converted tree is walked *in parallel with the
   template* and every leaf is ``device_put`` onto the template leaf's
   sharding. This is what makes a different chip count / mesh shape
   restore work: the template was built for the CURRENT mesh, so
   placement IS the reshard (orbax does the same thing natively when
   layouts agree; this path extends it to layout changes and to hot
   snapshots, which are raw host trees by construction).
3. **EF-residual re-bucketing** — a saved ``(L, data_old, padded_old)``
   error-feedback residual re-buckets onto the new data degree
   preserving the telescoping sum (``parallel/compress.
   rebucket_residual``, float tolerance); incompatible layouts
   zero-initialise with the long-standing warning instead of crashing.

Genuinely lossy mismatches (a leaf whose shape cannot be reached by
restacking — the model geometry or optimizer changed) still refuse, with
the mismatching leaf path named: resharding must never silently
truncate or broadcast state.

The same walk serialises live states into pure host trees
(:func:`to_pure` / :func:`from_pure_arrays`) for the hot-checkpoint
layer (``checkpoint/hot.py``), so hot and durable snapshots restore
through ONE placement path.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import get_logger

log = get_logger(__name__)

#: marker key for an array leaf inside a pure tree (the index into the
#: flat leaves list saved alongside)
LEAF_KEY = "__leaf__"
#: marker key for a non-array python literal (int/float/str/bool)
LIT_KEY = "__lit__"


def _is_array(x: Any) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _rebuild_seq(tmpl: Any, children: list) -> Any:
    """Reconstruct a sequence with converted children (NamedTuples —
    live optax states — need splat construction)."""
    if isinstance(tmpl, tuple) and hasattr(tmpl, "_fields"):
        return type(tmpl)(*children)
    return type(tmpl)(children)


# -- pure-tree serialisation (the hot-checkpoint wire format) -------------

def to_pure(tree: Any) -> tuple[Any, list[Any]]:
    """``(pure, leaves)``: the tree re-spelled in JSON-able containers
    (dicts / lists / ``{LEAF_KEY: i}`` markers / ``{LIT_KEY: v}``
    literals) plus the array leaves in marker order. Dataclasses (flax
    structs) become field dicts; NamedTuples and tuples become lists —
    the *template* reimposes the concrete types on restore, so the wire
    format stays schema-free."""
    leaves: list[Any] = []

    def walk(x: Any) -> Any:
        if x is None:
            return None
        if isinstance(x, dict):
            return {str(k): walk(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [walk(v) for v in x]
        if _is_array(x):
            leaves.append(x)
            return {LEAF_KEY: len(leaves) - 1}
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            return {f.name: walk(getattr(x, f.name))
                    for f in dataclasses.fields(x)}
        if isinstance(x, (bool, int, float, str)):
            return {LIT_KEY: x}
        raise TypeError(
            f"to_pure cannot serialise a {type(x).__name__} leaf — hot "
            "snapshots carry arrays, containers and literals only")

    return walk(tree), leaves


def from_pure_arrays(pure: Any, arrays: list[Any]) -> Any:
    """Substitute the flat ``arrays`` back into a :func:`to_pure` tree:
    the result is plain dicts/lists with numpy leaves — exactly the
    shape a template-free orbax restore produces, so the one
    :func:`reshard_onto_template` walk serves both."""
    if pure is None:
        return None
    if isinstance(pure, dict):
        if set(pure) == {LEAF_KEY}:
            return arrays[int(pure[LEAF_KEY])]
        if set(pure) == {LIT_KEY}:
            return pure[LIT_KEY]
        return {k: from_pure_arrays(v, arrays) for k, v in pure.items()}
    if isinstance(pure, (list, tuple)):
        return [from_pure_arrays(v, arrays) for v in pure]
    return pure


# -- placement ------------------------------------------------------------

def place_onto_template(tmpl: Any, tree: Any, path: str = "state") -> Any:
    """Walk ``tmpl`` and ``tree`` in parallel, placing every ``tree``
    leaf onto the corresponding template leaf's sharding (shape-checked;
    dtype cast to the template's). The template dictates structure —
    raw orbax/hot trees spell tuples as lists and structs as dicts, and
    this walk maps them back. Mismatches raise with the leaf path
    named."""
    if tmpl is None:
        return None
    if tree is None and not jax.tree.leaves(tmpl):
        # orbax's template-free restore spells empty containers (optax
        # EmptyState / empty tuples) as None; the template's leafless
        # structure is authoritative
        return tmpl
    if isinstance(tmpl, dict):
        if not isinstance(tree, dict):
            raise ValueError(
                f"reshard-on-restore: {path} is a mapping in the template "
                f"but a {type(tree).__name__} in the checkpoint")
        missing = sorted(set(map(str, tmpl)) - set(map(str, tree)))
        if missing:
            raise ValueError(
                f"reshard-on-restore: checkpoint lacks {path}/{missing[0]} "
                "(and possibly more) — the model/optimizer geometry "
                "changed since the save")
        extra = sorted(set(map(str, tree)) - set(map(str, tmpl)))
        if extra:
            # symmetric refusal: dropping saved state on the floor is a
            # silent truncation, exactly what this walk must never do
            raise ValueError(
                f"reshard-on-restore: checkpoint carries {path}/{extra[0]} "
                "(and possibly more) that this run's model/optimizer does "
                "not — the geometry changed since the save; resharding "
                "must not silently drop saved state")
        by_str = {str(k): v for k, v in tree.items()}
        return {k: place_onto_template(v, by_str[str(k)], f"{path}/{k}")
                for k, v in tmpl.items()}
    if isinstance(tmpl, (list, tuple)):
        if (isinstance(tmpl, tuple) and hasattr(tmpl, "_fields")
                and isinstance(tree, dict)):
            # orbax's template-free restore spells NamedTuples (optax
            # states) as field-name dicts; reorder by the template's
            # fields
            missing = [f for f in tmpl._fields if f not in tree]
            if missing:
                raise ValueError(
                    f"reshard-on-restore: checkpoint lacks "
                    f"{path}/{missing[0]} — the optimizer state changed "
                    "since the save")
            extra = sorted(set(tree) - set(tmpl._fields))
            if extra:
                raise ValueError(
                    f"reshard-on-restore: checkpoint carries "
                    f"{path}/{extra[0]} that this run's optimizer state "
                    "does not — the optimizer changed since the save; "
                    "resharding must not silently drop saved state")
            tree = [tree[f] for f in tmpl._fields]
        if not isinstance(tree, (list, tuple)) or len(tree) != len(tmpl):
            raise ValueError(
                f"reshard-on-restore: {path} holds {len(tmpl)} entries in "
                "the template but "
                f"{len(tree) if isinstance(tree, (list, tuple)) else type(tree).__name__} "
                "in the checkpoint")
        children = [place_onto_template(t, v, f"{path}[{i}]")
                    for i, (t, v) in enumerate(zip(tmpl, tree))]
        return _rebuild_seq(tmpl, children)
    if _is_array(tmpl):
        if not (_is_array(tree) or isinstance(tree, (int, float, bool))):
            raise ValueError(
                f"reshard-on-restore: {path} is an array in the template "
                f"but a {type(tree).__name__} in the checkpoint")
        arr = np.asarray(tree)
        if tuple(arr.shape) != tuple(tmpl.shape):
            raise ValueError(
                f"reshard-on-restore: leaf {path} has shape "
                f"{tuple(arr.shape)} in the checkpoint but "
                f"{tuple(tmpl.shape)} in this run's template — a "
                "genuinely lossy mismatch (model geometry/optimizer "
                "changed?); restacking cannot bridge it. Convert offline "
                "with tools/convert_checkpoint.py or pass --no_resume")
        if arr.dtype != tmpl.dtype:
            arr = arr.astype(tmpl.dtype)
        sharding = getattr(tmpl, "sharding", None)
        if sharding is not None:
            return jax.device_put(arr, sharding)
        return jnp.asarray(arr)
    if dataclasses.is_dataclass(tmpl) and not isinstance(tmpl, type):
        if not isinstance(tree, dict):
            raise ValueError(
                f"reshard-on-restore: {path} is a {type(tmpl).__name__} "
                f"in the template but a {type(tree).__name__} in the "
                "checkpoint")
        fields = {f.name: place_onto_template(getattr(tmpl, f.name),
                                              tree[f.name],
                                              f"{path}/{f.name}")
                  for f in dataclasses.fields(tmpl)}
        return type(tmpl)(**fields)
    # scalar/other template leaf: keep the checkpoint's value verbatim
    return tree


# -- the reshard entrypoint -----------------------------------------------

def reshard_onto_template(raw: Any, tmpl: Any, *,
                          desc: str = "checkpoint") -> Any:
    """Convert ``raw`` (a template-free host tree: orbax raw restore or
    a hot snapshot) into the template's layer layout (when they differ)
    and place every leaf onto the template's shardings. Returns the
    fully placed tree; raises with intent on genuinely lossy
    mismatches."""
    from ..parallel.stacking import (
        convert_tree_layout, detect_layer_layout, detect_pipe_stages,
    )

    src_pipe = detect_pipe_stages(raw)
    src = "pipelined" if src_pipe else detect_layer_layout(raw)
    dst_pipe = detect_pipe_stages(tmpl)
    dst = "pipelined" if dst_pipe else detect_layer_layout(tmpl)
    if (src, src_pipe) != (dst, dst_pipe) and src != "none":
        log.info(
            "reshard-on-restore: converting %s layer layout %s -> %s "
            "in-restore (bit-exact restack; the offline "
            "tools/convert_checkpoint.py run is no longer required)",
            desc,
            src if src_pipe is None else f"{src}({src_pipe} stages)",
            dst if dst_pipe is None else f"{dst}({dst_pipe} stages)")
        raw = convert_tree_layout(raw, dst, pipe_stages=dst_pipe,
                                  strict=False)
    return place_onto_template(tmpl, raw)


def place_state_onto_template(template_state: Any, raw_body: Any,
                              raw_residual: Any = None, *,
                              desc: str = "checkpoint") -> Any:
    """THE one placement path: map a template-free ``(body, residual)``
    pair — a raw orbax restore or a hot snapshot — onto a live
    ``template_state``. Converts the layer layout, places every leaf
    onto the template's shardings, and maps the EF residual (direct /
    re-bucketed / zero-init-with-warning). Both the durable
    ``CheckpointManager.restore_resharded`` and the engine's hot-tier
    restore call here, so a placement fix can never land in one tier
    and miss the other."""
    from .manager import _split_residual

    body_tmpl, res_tmpl = _split_residual(template_state)
    placed = reshard_onto_template(raw_body, body_tmpl, desc=desc)
    if body_tmpl is template_state:
        return placed  # non-dataclass tree (tools): no residual split
    state = template_state.replace(**placed)
    if res_tmpl is not None:
        restored_res = (restore_residual_onto(res_tmpl, raw_residual)
                        if raw_residual is not None else None)
        if restored_res is not None:
            state = state.replace(comm_residual=restored_res)
        else:
            log.warning(
                "%s carries no compatible comm_residual — error-feedback "
                "residual zero-initialised (expected for pre-residual "
                "checkpoints or after changing --grad_comm/topology; "
                "fresh runs recommended when changing comm settings)",
                desc)
    return state


def restore_residual_onto(res_tmpl: Any, raw_res: Any) -> Any | None:
    """Map a saved EF-residual tree onto the template residual: direct
    placement when shapes agree, the telescoping-preserving re-bucketing
    when only the data degree changed, ``None`` (caller keeps the zero
    init) when the layouts are genuinely incompatible."""
    from ..parallel.compress import rebucket_residual

    tl = jax.tree.leaves(res_tmpl)
    rl = (jax.tree.leaves(raw_res)
          if not isinstance(raw_res, (list, tuple))
          else list(raw_res))
    if len(tl) != len(rl):
        return None
    placed = []
    rebucketed = False
    for t, r in zip(tl, rl):
        r = np.asarray(r)
        if tuple(r.shape) == tuple(t.shape):
            pass
        elif (r.ndim == 3 and t.ndim == 3
              and r.shape[0] == t.shape[0]):
            r = rebucket_residual(r, tuple(t.shape))
            rebucketed = True
        else:
            return None
        sharding = getattr(t, "sharding", None)
        arr = r.astype(t.dtype)
        placed.append(jax.device_put(arr, sharding)
                      if sharding is not None else jnp.asarray(arr))
    if rebucketed:
        log.info(
            "reshard-on-restore: error-feedback residual re-bucketed "
            "onto the new data degree (telescoping sum preserved at "
            "float tolerance; per-replica attribution reset)")
    structure = jax.tree.structure(res_tmpl)
    return jax.tree.unflatten(structure, placed)
