"""Materialise a model-zoo synthetic dataset into a memory-mapped store.

The real-data rung (``--data_dir``) trains from disk; this tool fabricates
the disk artifact so the file-backed path is exercisable without shipping
a corpus (the reference ships none either — its data is ``torch.randn``,
``/root/reference/dataset.py:10-11``).

Usage::

    python tools/make_file_dataset.py --model resnet18 --samples 50000 \
        --out /tmp/cifar_store
    python ddp.py --model resnet18 --data_dir /tmp/cifar_store ...
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="resnet18",
                   help="model-zoo key whose paired dataset to materialise")
    p.add_argument("--samples", type=int, default=10_000)
    p.add_argument("--out", required=True)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--chunk", type=int, default=1024)
    p.add_argument("--cpu", action="store_true",
                   help="Force the CPU backend (the axon TPU plugin hangs "
                        "on a dead relay; dataset materialisation never "
                        "needs the chip).")
    args = p.parse_args(argv)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.data.filestore import materialize
    from pytorch_ddp_template_tpu.models import build

    config = TrainingConfig(model=args.model, dataset_size=args.samples,
                            seed=args.seed)
    _, dataset = build(args.model, config)
    t0 = time.perf_counter()
    path = materialize(dataset, args.out, samples=args.samples,
                       chunk=args.chunk)
    dt = time.perf_counter() - t0
    total = sum(f.stat().st_size for f in path.glob("*.bin"))
    print(f"wrote {args.samples} samples ({total / 1e6:.1f} MB) to {path} "
          f"in {dt:.1f}s ({total / dt / 1e6:.0f} MB/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
