#!/usr/bin/env bash
# Thin shim (r15 consolidation): see tools/tpu_poller.sh — this spelling
# kept so committed docs keep working.
exec bash "$(dirname "$0")/tpu_poller.sh" 14
