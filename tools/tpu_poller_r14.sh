#!/usr/bin/env bash
# Round-14 tunnel poller: probe the axon relay port every 60s; when it
# answers twice in a row (10s apart), run the round-14 suite once and
# exit. The r14 suite chains the r13 backlog FIRST (which itself leads
# with the r12/r11/r10/r9/r8/r7 chains and the r6 e2e headline pair),
# then records the fleet-watchtower legs — the BENCH_MODE=fleet
# neutrality pair with live /status + /metrics scrapes, the
# injected-straggler bundle, the perf_baseline restore-compare across
# two runs of one output_dir, and tools/bench_diff.py over the
# committed records (fleet exchange DEGENERATE on a 1-host tunnel; real
# multi-host rows need launch/run_pod.sh on >= 2 workers). Gives up
# after ~11 h.
set -u
cd "$(dirname "$0")/.."
probe() { timeout 2 bash -c '</dev/tcp/127.0.0.1/8082' 2>/dev/null; }
deadline=$(( $(date +%s) + 39600 ))
while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    sleep 10
    if probe; then
      echo "tunnel up at $(date -u +%FT%TZ); running r14 followup suite" >&2
      bash tools/tpu_followup_r14.sh
      exit $?
    fi
  fi
  sleep 60
done
echo "poller gave up: tunnel never answered" >&2
exit 3
