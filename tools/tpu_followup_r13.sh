#!/usr/bin/env bash
# Round-13 TPU measurement suite. Ordering per the established pattern:
# (1) the r12 backlog FIRST (tools/tpu_followup_r12.sh — itself chaining
# r11/r10/r9/r8/r7, headed by the still-open r6 e2e host-overhead
# headline pair), then (2) the round-13 performance-attribution legs on
# the real chip. The r13 real-hardware data this CPU host cannot
# produce: (a) a REAL MFU — the CPU record's calibrated peak proves
# pipeline consistency only; on v5e the PEAK_FLOPS table entry applies
# and the reported perf_mfu is a true model-FLOPs utilisation, directly
# comparable to tools/mfu_probe.py's number for the same config;
# (b) a trace with the named loop/schedule phases — the --perf_report
# --profile_steps run below leaves a profile whose host lanes read
# input_wait / train_step_dispatch / device_wait and whose device lanes
# carry the sched_* named scopes (copy the profile dir next to the
# records for the round's evidence); (c) real compute/comm splits — on
# a multi-chip slice the ICI table engages and perf_frac_comm becomes
# meaningful (single chip: wire bytes 0, frac_comm 0, flagged by the
# record's mesh fields, the r8 degenerate convention).
# Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r13.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, outfile, env... — logs one JSON line or the error
  local name=$1 out=$2; shift 2
  echo "=== $name ===" >&2
  env "$@" timeout 1800 python bench.py 2>>"$R/.followup_r13.err" | tee -a "$R/$out"
}

# 1. the r12 backlog first (r11/r10/r9/r8/r7 chain -> obs legs)
bash tools/tpu_followup_r12.sh
rc12=$?

# 2. round-13 performance-attribution legs
#    (a) BENCH_MODE=perf on the chip: neutrality pair against real
#        device-bound steps + a REAL MFU (v5e is in the PEAK_FLOPS
#        table, so no calibration — mfu_reported is the true number)
run perf_legs perf_tpu_r13.jsonl BENCH_MODE=perf BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_STEPS=20 BENCH_WARMUP=3 BENCH_LOG_STEPS=5
#    (b) cross-check: tools/mfu_probe.py full_step MFU for the same
#        config must agree with (a)'s mfu_reported (both are model
#        FLOPs / wall / peak; disagreement means the attribution
#        interval math drifted from the probe's fenced timing)
timeout 900 python tools/mfu_probe.py --model gpt-small --batch 4 \
  2>>"$R/.followup_r13.err" | tee -a "$R/perf_tpu_r13.jsonl"
#    (c) a named-phase trace: --perf_report + --profile_steps through
#        the production loop; the profile lands in the run dir — copy
#        it next to the records (host lanes: input_wait/dispatch/
#        device_wait; device lanes: sched_* scopes)
timeout 900 python ddp.py --model gpt-small --scan_layers --perf_report \
  --profile_steps 6 --max_steps 30 --per_device_train_batch_size 4 \
  --logging_steps 5 --save_steps 0 --dataset_size 2048 --no_resume \
  --output_dir /tmp/perf_trace_tpu_r13 2>>"$R/.followup_r13.err" \
  && cp -r /tmp/perf_trace_tpu_r13/profile "$R/perf_trace_tpu_r13_profile" \
  && cp /tmp/perf_trace_tpu_r13/goodput.json "$R/goodput_tpu_r13.json" \
  && echo "trace + goodput copied into $R/" >&2

echo "done; r13 records in $R/perf_tpu_r13.jsonl" >&2
exit $rc12
