#!/usr/bin/env bash
# One-command bench regression gate (r15 satellite): wire
# tools/bench_diff.py over the committed bench_records/ as a CI check.
#
#   bash tools/ci_bench_check.sh                 # self-check: committed
#                                                # records vs themselves
#                                                # (must exit 0 — proves
#                                                # the tripwire is armed
#                                                # and the records parse)
#   bash tools/ci_bench_check.sh /tmp/fresh      # gate: fresh records
#                                                # (a dir or .jsonl of
#                                                # bench.py output) vs
#                                                # the committed ones
#   TOLERANCE=0.15 bash tools/ci_bench_check.sh /tmp/fresh
#   RUN_ALL=1 bash tools/ci_bench_check.sh       # r22: every fresh leg
#                                                # below in one go — the
#                                                # nightly spelling (sets
#                                                # all RUN_* flags; budget
#                                                # ~1.5h on a cold CPU)
#
# Per-leg fresh-run flags — each runs one BENCH_MODE on this host and
# gates its record against the committed one (the gate table):
#
#   flag              mode          round  committed record it gates
#   ----------------  ------------  -----  -------------------------------
#   RUN_ELASTIC=1     elastic       r18    elastic_cpu_r18.jsonl
#                                          (crash->resume MTTR + fallback)
#   RUN_SERVE=1       serve         r19    serve_cpu_r19.jsonl
#                                          (continuous-vs-static tok/s,
#                                          zero-recompile pin, gauges)
#   RUN_SPEC=1        spec          r20    spec_cpu_r20.jsonl
#                                          (acceptance + FLOPs-adjusted
#                                          win, lossless re-check)
#   RUN_SERVE_TP=1    serve_tp      r21    serve_tp_cpu_r21.jsonl
#                                          (tp decode parity, one-program
#                                          pin, HLO ring evidence)
#   RUN_PIPE_COMPOSE=1 pipe_compose r22    pipe_compose_cpu_r22.jsonl
#                                          (pipe×tp / pipe×ddp parity,
#                                          branch-collective-free HLO)
#
# Modes not listed (train/pipe/quant/...) are exercised by the tier-1
# suite's contract tests; their committed records still participate in
# the default self-check and in any directory-vs-directory gate.
#
# Exit codes are bench_diff's: 0 in-band, 1 drift, 2 no overlap/usage
# (an empty comparison must not read as green). Output is the github
# markdown table — paste-ready for a PR comment / CI job summary.
set -u
cd "$(dirname "$0")/.."
R=bench_records
CANDIDATE=${1:-$R}
TOLERANCE=${TOLERANCE:-0.25}

# RUN_ALL=1 is sugar for every per-leg flag (the nightly spelling)
if [ "${RUN_ALL:-0}" = "1" ]; then
  RUN_SERVE=1 RUN_SPEC=1 RUN_SERVE_TP=1 RUN_ELASTIC=1 RUN_PIPE_COMPOSE=1
fi

# fresh-leg flags share ONE scratch dir so RUN_SERVE=1 RUN_ELASTIC=1
# gates both records (a later block overwriting CANDIDATE would silently
# discard the earlier run)
if [ "${RUN_SERVE:-0}" = "1" ] || [ "${RUN_ELASTIC:-0}" = "1" ] \
    || [ "${RUN_SPEC:-0}" = "1" ] || [ "${RUN_SERVE_TP:-0}" = "1" ] \
    || [ "${RUN_PIPE_COMPOSE:-0}" = "1" ]; then
  FRESH_DIR=$(mktemp -d)
  CANDIDATE=$FRESH_DIR
fi

if [ "${RUN_SERVE:-0}" = "1" ]; then
  # the serve leg runs the mixed-length workload on a warmed engine
  # (compile pass + timed pass per policy)
  BENCH_CPU=${BENCH_CPU:-1} BENCH_MODE=serve \
    timeout 900 python bench.py | tee "$FRESH_DIR/serve_fresh.jsonl"
fi

if [ "${RUN_SPEC:-0}" = "1" ]; then
  # the spec leg replays the serve workload through the speculative
  # engine (draft + one-dispatch verify) against the plain engine,
  # re-checking losslessness inside the run
  BENCH_CPU=${BENCH_CPU:-1} BENCH_MODE=spec \
    timeout 1200 python bench.py | tee "$FRESH_DIR/spec_fresh.jsonl"
fi

if [ "${RUN_SERVE_TP:-0}" = "1" ]; then
  # the tp leg needs a model:2 axis — two virtual CPU devices; parity,
  # the compile pin and the ring-evidence AOT compile ride one run
  BENCH_CPU=${BENCH_CPU:-1} BENCH_CPU_DEVICES=${BENCH_CPU_DEVICES:-2} \
    BENCH_MODE=serve_tp \
    timeout 1200 python bench.py | tee "$FRESH_DIR/serve_tp_fresh.jsonl"
fi

if [ "${RUN_ELASTIC:-0}" = "1" ]; then
  # the elastic legs run the full crash->resume episodes, so give them
  # their own timeout
  BENCH_CPU=${BENCH_CPU:-1} BENCH_CPU_DEVICES=${BENCH_CPU_DEVICES:-8} \
    BENCH_MODE=elastic BENCH_STEPS=${BENCH_STEPS:-20} \
    BENCH_WARMUP=${BENCH_WARMUP:-3} \
    timeout 1800 python bench.py | tee "$FRESH_DIR/elastic_fresh.jsonl"
fi

if [ "${RUN_PIPE_COMPOSE:-0}" = "1" ]; then
  # the compose legs carve pipe×tp (data:2,model:2,pipe:2) and
  # pipe×ddp (data:4,pipe:2) from 8 virtual devices: parity vs
  # sequential stages, FLOPs-matched step ratios, and the r22
  # branch-collective-free HLO tripwire in one run
  BENCH_CPU=${BENCH_CPU:-1} BENCH_CPU_DEVICES=${BENCH_CPU_DEVICES:-8} \
    BENCH_MODE=pipe_compose \
    timeout 1800 python bench.py | tee "$FRESH_DIR/pipe_compose_fresh.jsonl"
fi

python tools/bench_diff.py "$R" "$CANDIDATE" \
  --tolerance "$TOLERANCE" --format github
rc=$?
if [ "$CANDIDATE" = "$R" ] && [ "$rc" -eq 0 ]; then
  echo >&2
  echo "self-check passed: committed records parse and are in-band vs themselves" >&2
  echo "(run with a fresh records dir to gate new numbers: tools/ci_bench_check.sh <dir>)" >&2
fi
exit $rc
