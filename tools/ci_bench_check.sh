#!/usr/bin/env bash
# One-command bench regression gate (r15 satellite): wire
# tools/bench_diff.py over the committed bench_records/ as a CI check.
#
#   bash tools/ci_bench_check.sh                 # self-check: committed
#                                                # records vs themselves
#                                                # (must exit 0 — proves
#                                                # the tripwire is armed
#                                                # and the records parse)
#   bash tools/ci_bench_check.sh /tmp/fresh      # gate: fresh records
#                                                # (a dir or .jsonl of
#                                                # bench.py output) vs
#                                                # the committed ones
#   TOLERANCE=0.15 bash tools/ci_bench_check.sh /tmp/fresh
#   RUN_ELASTIC=1 bash tools/ci_bench_check.sh  # r18: run BENCH_MODE=elastic
#                                               # fresh (CPU, crash->resume
#                                               # MTTR + fallback legs) and
#                                               # gate it vs the committed
#                                               # elastic record
#   RUN_SERVE=1 bash tools/ci_bench_check.sh    # r19: run BENCH_MODE=serve
#                                               # fresh (CPU: continuous-vs-
#                                               # static tokens/sec, the
#                                               # zero-recompile pin, live
#                                               # gauges) and gate it vs the
#                                               # committed serve record
#   RUN_SPEC=1 bash tools/ci_bench_check.sh     # r20: run BENCH_MODE=spec
#                                               # fresh (CPU: speculative
#                                               # acceptance + FLOPs-adjusted
#                                               # win, lossless re-check, the
#                                               # two-program pin) and gate it
#                                               # vs the committed spec record
#   RUN_SERVE_TP=1 bash tools/ci_bench_check.sh # r21: run BENCH_MODE=serve_tp
#                                               # fresh (CPU, 2 virtual
#                                               # devices: token-for-token
#                                               # parity vs single replica,
#                                               # the one-program pin, HLO
#                                               # ring evidence) and gate it
#                                               # vs the committed record
#
# Exit codes are bench_diff's: 0 in-band, 1 drift, 2 no overlap/usage
# (an empty comparison must not read as green). Output is the github
# markdown table — paste-ready for a PR comment / CI job summary.
set -u
cd "$(dirname "$0")/.."
R=bench_records
CANDIDATE=${1:-$R}
TOLERANCE=${TOLERANCE:-0.25}

# fresh-leg flags share ONE scratch dir so RUN_SERVE=1 RUN_ELASTIC=1
# gates both records (a later block overwriting CANDIDATE would silently
# discard the earlier run)
if [ "${RUN_SERVE:-0}" = "1" ] || [ "${RUN_ELASTIC:-0}" = "1" ] \
    || [ "${RUN_SPEC:-0}" = "1" ] || [ "${RUN_SERVE_TP:-0}" = "1" ]; then
  FRESH_DIR=$(mktemp -d)
  CANDIDATE=$FRESH_DIR
fi

if [ "${RUN_SERVE:-0}" = "1" ]; then
  # the serve leg runs the mixed-length workload on a warmed engine
  # (compile pass + timed pass per policy)
  BENCH_CPU=${BENCH_CPU:-1} BENCH_MODE=serve \
    timeout 900 python bench.py | tee "$FRESH_DIR/serve_fresh.jsonl"
fi

if [ "${RUN_SPEC:-0}" = "1" ]; then
  # the spec leg replays the serve workload through the speculative
  # engine (draft + one-dispatch verify) against the plain engine,
  # re-checking losslessness inside the run
  BENCH_CPU=${BENCH_CPU:-1} BENCH_MODE=spec \
    timeout 1200 python bench.py | tee "$FRESH_DIR/spec_fresh.jsonl"
fi

if [ "${RUN_SERVE_TP:-0}" = "1" ]; then
  # the tp leg needs a model:2 axis — two virtual CPU devices; parity,
  # the compile pin and the ring-evidence AOT compile ride one run
  BENCH_CPU=${BENCH_CPU:-1} BENCH_CPU_DEVICES=${BENCH_CPU_DEVICES:-2} \
    BENCH_MODE=serve_tp \
    timeout 1200 python bench.py | tee "$FRESH_DIR/serve_tp_fresh.jsonl"
fi

if [ "${RUN_ELASTIC:-0}" = "1" ]; then
  # the elastic legs run the full crash->resume episodes, so give them
  # their own timeout
  BENCH_CPU=${BENCH_CPU:-1} BENCH_CPU_DEVICES=${BENCH_CPU_DEVICES:-8} \
    BENCH_MODE=elastic BENCH_STEPS=${BENCH_STEPS:-20} \
    BENCH_WARMUP=${BENCH_WARMUP:-3} \
    timeout 1800 python bench.py | tee "$FRESH_DIR/elastic_fresh.jsonl"
fi

python tools/bench_diff.py "$R" "$CANDIDATE" \
  --tolerance "$TOLERANCE" --format github
rc=$?
if [ "$CANDIDATE" = "$R" ] && [ "$rc" -eq 0 ]; then
  echo >&2
  echo "self-check passed: committed records parse and are in-band vs themselves" >&2
  echo "(run with a fresh records dir to gate new numbers: tools/ci_bench_check.sh <dir>)" >&2
fi
exit $rc
