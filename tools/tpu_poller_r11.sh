#!/usr/bin/env bash
# Round-11 tunnel poller: probe the axon relay port every 60s; when it
# answers twice in a row (10s apart), run the round-11 suite once and
# exit. The r11 suite chains the r10 backlog FIRST (which itself leads
# with the r9/r8/r7 chains and the r6 e2e headline pair), then records
# the composed-schedule legs (degenerate marker at 1 chip — the real
# composed record needs a data×model multi-chip slice). Gives up after
# ~11 h.
set -u
cd "$(dirname "$0")/.."
probe() { timeout 2 bash -c '</dev/tcp/127.0.0.1/8082' 2>/dev/null; }
deadline=$(( $(date +%s) + 39600 ))
while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    sleep 10
    if probe; then
      echo "tunnel up at $(date -u +%FT%TZ); running r11 followup suite" >&2
      bash tools/tpu_followup_r11.sh
      exit $?
    fi
  fi
  sleep 60
done
echo "poller gave up: tunnel never answered" >&2
exit 3
