"""MFU probe: where does the train step's time go, per XLA's own numbers?

Decomposes one benchmark config into forward-only / forward+backward /
full-optimizer-step executables, timing each and reporting XLA cost
analysis (flops, bytes accessed → arithmetic intensity), so MFU tuning is
driven by measurement rather than guesses (VERDICT.md round-3 weak #2: the
resnet50 MFU of 0.249 had never been decomposed).

Usage (TPU or CPU):
    python tools/mfu_probe.py --model resnet50 --batch 256
    python tools/mfu_probe.py --model resnet50 --batch 256 --norm-dtype bf16

Emits one JSON line per measurement, suitable for bench_records/.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def timed(fn, *args, iters: int = 20, warmup: int = 3) -> float:
    """Time ``fn`` (which must return a scalar array). Sync is a host read
    of that scalar: on the axon tunnel ``block_until_ready`` can return
    before compute finishes (see bench.py), but device execution is
    in-order, so fetching a value produced by the LAST enqueued call fences
    the whole run."""
    import numpy as np

    for _ in range(warmup):
        out = fn(*args)
    float(np.asarray(out))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(np.asarray(out))
    return (time.perf_counter() - t0) / iters


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--batch", type=int, default=0, help="0 = bench default")
    ap.add_argument("--norm-dtype", default=None, choices=["f32", "bf16"],
                    help="ResNet BatchNorm compute-dtype ablation")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialise residual blocks (ResNet ablation)")
    ap.add_argument("--save-convs", action="store_true",
                    help="with --remat: selective policy — save conv "
                         "outputs by name, recompute only norm/ReLU")
    ap.add_argument("--stem", default=None,
                    choices=["imagenet", "space_to_depth"],
                    help="ResNet stem ablation (space_to_depth folds 2x2 "
                         "pixels into channels before the first conv)")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import bench
    from bench import init_devices

    init_devices()  # honours BENCH_CPU=1 and guards against a dead tunnel
    # the one shared copy of the cost/peak helpers (obs/attribution.py,
    # r13) — bench.py re-exports them from the same home
    from pytorch_ddp_template_tpu.obs.attribution import (
        cost_of, peak_flops_for,
    )
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.parallel import shard_tree
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    per_device = args.batch or bench.default_batch(args.model)
    devices = jax.devices()
    n_dev = len(devices)
    mesh = make_mesh(f"data:{n_dev}", devices)
    config = TrainingConfig(
        model=args.model, mesh=f"data:{n_dev}",
        per_device_train_batch_size=per_device, bf16=True,
        dataset_size=per_device * n_dev * 2, warmup_steps=0,
        max_grad_norm=1000.0,
    )
    task, dataset = build(args.model, config, mesh=mesh)
    if args.norm_dtype is not None:
        # rebuild the module with the requested BatchNorm compute dtype
        nd = jnp.bfloat16 if args.norm_dtype == "bf16" else jnp.float32
        task.model = task.model.clone(norm_dtype=nd)
    if args.remat:
        task.model = task.model.clone(
            remat=True, **({"remat_save_convs": True} if args.save_convs
                           else {}))
    if args.stem:
        task.model = task.model.clone(stem=args.stem)

    global_batch = per_device * n_dev
    idx = np.arange(global_batch) % len(dataset)
    batch = {
        k: jax.device_put(v, NamedSharding(mesh, P("data")))
        for k, v in dataset.batch(idx).items()
    }
    seed_key = jax.random.PRNGKey(0)
    params, extra = task.init(seed_key, batch)
    tx, schedule = make_optimizer(config, total_steps=10_000)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       extra_vars=extra, opt_state=tx.init(params),
                       rng=jax.random.clone(seed_key))
    state = shard_tree(state, mesh)

    # three rungs: fwd-only, fwd+bwd (no update), full optimizer step
    def fwd(params, extra_vars, batch, rng):
        loss, _, _ = task.loss(params, extra_vars, batch, rng, train=True)
        return loss

    def fwd_bwd(params, extra_vars, batch, rng):
        def lf(p):
            loss, new_extra, _ = task.loss(p, extra_vars, batch, rng, train=True)
            return loss, new_extra
        (loss, _), grads = jax.value_and_grad(lf, has_aux=True)(params)
        return loss, grads

    rng = jax.random.fold_in(seed_key, 1)
    fwd_c = jax.jit(fwd).lower(state.params, state.extra_vars, batch, rng).compile()
    bwd_c = jax.jit(fwd_bwd).lower(state.params, state.extra_vars, batch, rng).compile()
    step_c = make_train_step(task, tx, schedule, accum_steps=1).lower(
        state, batch).compile()

    kind = devices[0].device_kind
    peak = peak_flops_for(kind)
    t_step = None

    # the step donates its input state; rethread it every call
    holder = {"state": state}

    def step_call():
        holder["state"], m = step_c(holder["state"], batch)
        return m["loss"]

    for name, compiled, call in (
        ("fwd", fwd_c, lambda: fwd_c(state.params, state.extra_vars, batch, rng)),
        ("fwd_bwd", bwd_c,
         lambda: bwd_c(state.params, state.extra_vars, batch, rng)[0]),
        ("full_step", step_c, step_call),
    ):
        t = timed(call, iters=args.iters)
        c = cost_of(compiled)
        row = {
            "probe": name, "model": args.model, "batch": global_batch,
            "norm_dtype": args.norm_dtype or "f32", "remat": args.remat,
            **({"remat_policy": "save-convs"} if args.save_convs else {}),
            **({"stem": args.stem} if args.stem else {}),
            "time_ms": round(t * 1e3, 3),
            "gflops": round(c["flops"] / 1e9, 2),
            "gbytes": round(c["bytes"] / 1e9, 3),
            "intensity_flops_per_byte": round(c["flops"] / c["bytes"], 1)
            if c["bytes"] else None,
            "tflops_per_sec": round(c["flops"] / t / 1e12, 2),
            "device_kind": kind,
        }
        if peak:
            row["mfu"] = round(c["flops"] / t / peak, 4)
            # roofline: what the step time would be if HBM (~819 GB/s on
            # v5e) or the MXU were the only limit
            row["hbm_bound_ms"] = round(c["bytes"] / 819e9 * 1e3, 3)
            row["mxu_bound_ms"] = round(c["flops"] / peak * 1e3, 3)
        if name == "full_step":
            t_step = t
        print(json.dumps(row), flush=True)

    imgs = global_batch / t_step
    print(json.dumps({"probe": "throughput", "model": args.model,
                      "norm_dtype": args.norm_dtype or "f32",
                      "examples_per_sec_per_chip": round(imgs / n_dev, 1)}),
          flush=True)


if __name__ == "__main__":
    main()
