#!/usr/bin/env bash
# Round-7 TPU measurement suite. Ordering per the "headline number first"
# directive: (1) the r6 headline e2e host-overhead pair (still the open
# headline — two rounds of dead tunnel), then (2) the round-7 scan-over-
# layers legs: the compile-time pair on the TPU backend (the CPU pair is
# already committed in bench_records/compile_scan_cpu_r7.jsonl; the TPU
# compiler is the number production cares about) and a deep-model
# (24-layer gpt-small) step-time pair proving the scan is throughput-
# neutral on real hardware, then (3) the deferred r4/r5 backlogs.
# Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r7.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, outfile, env... — logs one JSON line or the error
  local name=$1 out=$2; shift 2
  echo "=== $name ===" >&2
  env "$@" timeout 900 python bench.py 2>>"$R/.followup_r7.err" | tee -a "$R/$out"
}

# 1. HEADLINE FIRST: the r6 e2e host-overhead pair on the flagship config.
run e2e_sync  host_overhead_tpu_r6.jsonl BENCH_MODE=e2e BENCH_MODEL=resnet50 BENCH_LOG_STEPS=1 BENCH_TELEMETRY=sync
run e2e_async host_overhead_tpu_r6.jsonl BENCH_MODE=e2e BENCH_MODEL=resnet50 BENCH_LOG_STEPS=1 BENCH_TELEMETRY=async

# 2. round-7 scan-over-layers legs
#    (a) compile-time sweep, unrolled vs scanned at depth 2/12/24, on the
#        TPU compiler (Mosaic/XLA:TPU pays more per block than XLA:CPU, so
#        the win should be LARGER here than the committed CPU pair)
run compile_sweep compile_scan_tpu_r7.jsonl BENCH_MODE=compile
#    (b) deep-model step-time pair: gpt-small at 24 layers, unrolled vs
#        scanned (BENCH_DEPTH marks the records as non-headline variants);
#        scan_layers must be throughput-neutral within run-to-run noise
run deep24_unrolled compile_scan_tpu_r7.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_DEPTH=24 BENCH_BATCH=4
run deep24_scanned  compile_scan_tpu_r7.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_DEPTH=24 BENCH_BATCH=4 BENCH_SCAN=1
#    (c) remat-scan memory evidence: same deep pair with remat on — the
#        memory_analysis fields (temp_mb) in the record are the datum
run deep24_remat_unrolled compile_scan_tpu_r7.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_DEPTH=24 BENCH_BATCH=4 BENCH_REMAT=1
run deep24_remat_scanned  compile_scan_tpu_r7.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_DEPTH=24 BENCH_BATCH=4 BENCH_REMAT=1 BENCH_SCAN=1

# 3. then the deferred round-4/5 backlogs, unchanged
bash tools/tpu_followup_r4.sh
rc4=$?
bash tools/tpu_followup_r5.sh
rc5=$?

echo "done; r7 records in $R/compile_scan_tpu_r7.jsonl" >&2
exit $(( rc4 > rc5 ? rc4 : rc5 ))
