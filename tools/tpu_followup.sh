#!/usr/bin/env bash
# Consolidated TPU measurement suite (r15 satellite): ONE parameterized
# script replacing the accumulating per-round tpu_followup_rN.sh copies
# (the old spellings remain as thin shims so committed docs keep
# working). `bash tools/tpu_followup.sh <round>` runs the historical
# chain for that round — the same legs, outfiles and env the per-round
# scripts recorded:
#
#   round 4/5  : just that round's legs (the pre-chain era)
#   round 6    : the r6 e2e host-overhead pairs, then the r4/r5 backlogs
#   round >= 7 : the r6 e2e HEADLINE pair FIRST (still the open headline
#                — per the round-5 verdict's "headline number first"
#                directive), then the r7 legs, the r4/r5 backlogs, then
#                each later round's legs in order up to <round>
#
# Per-round notes (degenerate markers, multi-chip prerequisites, what a
# 1-chip tunnel can and cannot prove) live in the legs_rN functions
# below, carried over verbatim from the originals. Safe to re-run; each
# bench mode appends one JSON line to its round's records file.
# Usage: bash tools/tpu_followup.sh <round>   (requires the axon tunnel)
set -u
ROUND=${1:?usage: tpu_followup.sh <round: 4..22>}
case "$ROUND" in (*[!0-9]*|'') echo "round must be a number, got '$ROUND'" >&2; exit 2;; esac
if [ "$ROUND" -lt 4 ] || [ "$ROUND" -gt 22 ]; then
  echo "unknown round $ROUND (expected 4..22)" >&2; exit 2
fi
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"
ERR="$R/.followup_r${ROUND}.err"
RC=0

run() { # name, outfile, timeout_s, env... — one JSON line or the error
  local name=$1 out=$2 to=$3; shift 3
  echo "=== $name ===" >&2
  env "$@" timeout "$to" python bench.py 2>>"$ERR" | tee -a "$R/$out"
  local rc=${PIPESTATUS[0]}
  [ "$rc" -ne 0 ] && { echo "leg $name exited rc=$rc" >&2; RC=1; }
}

# the XLA latency-hiding-scheduler flag pack the r8-r11 A/B legs toggle
LHS="--xla_tpu_enable_latency_hiding_scheduler=true --xla_tpu_enable_async_collective_fusion=true --xla_tpu_enable_async_collective_fusion_fuse_all_gather=true --xla_tpu_enable_async_collective_fusion_multiple_steps=true --xla_tpu_overlap_compute_collective_tc=true --xla_enable_async_all_gather=true"

headline_e2e() {
  # the r6 e2e host-overhead pair on the flagship config — recorded
  # FIRST on every tunnel window since r7 ("headline number first")
  run e2e_sync  host_overhead_tpu_r6.jsonl 900 BENCH_MODE=e2e BENCH_MODEL=resnet50 BENCH_LOG_STEPS=1 BENCH_TELEMETRY=sync
  run e2e_async host_overhead_tpu_r6.jsonl 900 BENCH_MODE=e2e BENCH_MODEL=resnet50 BENCH_LOG_STEPS=1 BENCH_TELEMETRY=async
}

legs_r4() {
  # flash seq sweep (incl. the backward kernels), bert under the
  # dispatch policy, TPU e2e, long-context in situ, fused-head ablation
  run flash512  followup_tpu_r4.jsonl 900 BENCH_MODE=flash BENCH_SEQ=512
  run flash1024 followup_tpu_r4.jsonl 900 BENCH_MODE=flash BENCH_SEQ=1024
  run flash2048 followup_tpu_r4.jsonl 900 BENCH_MODE=flash BENCH_SEQ=2048
  run flash4096 followup_tpu_r4.jsonl 900 BENCH_MODE=flash BENCH_SEQ=4096
  run bert      followup_tpu_r4.jsonl 900 BENCH_MODE=train BENCH_MODEL=bert-base
  run e2e_rn50  followup_tpu_r4.jsonl 900 BENCH_MODE=e2e BENCH_MODEL=resnet50
  run gpt_long  followup_tpu_r4.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10
  run gpt_small followup_tpu_r4.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-small
  run gpt_small_fused followup_tpu_r4.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_FUSED_HEAD=1
  run bert_fused followup_tpu_r4.jsonl 900 BENCH_MODE=train BENCH_MODEL=bert-base BENCH_FUSED_HEAD=1
  echo "=== mfu_probe bert-base ===" >&2
  timeout 900 python tools/mfu_probe.py --model bert-base --iters 10 \
    | tee -a "$R/mfu_probe_bert_tpu_r4.jsonl" || RC=1
}

legs_r5() {
  # the gpt-long fused-stack story: each lever ablated, plus fresh
  # flagship numbers and the selective-remat mfu probes
  run gpt_long_fused   train_tpu_r5.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10
  run gpt_long_dense   train_tpu_r5.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10 BENCH_DENSE_HEAD=1
  run gpt_long_noflash train_tpu_r5.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10 FLASH_DISABLE=1
  run gpt_long_dense_noflash train_tpu_r5.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10 BENCH_DENSE_HEAD=1 FLASH_DISABLE=1
  run flash4096_b4 train_tpu_r5.jsonl 900 BENCH_MODE=flash BENCH_SEQ=4096
  run resnet50  train_tpu_r5.jsonl 900 BENCH_MODE=train BENCH_MODEL=resnet50
  run gpt_small train_tpu_r5.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-small
  local flags
  for flags in "" "--remat" "--remat --save-convs"; do
    echo "=== mfu_probe resnet50 $flags ===" >&2
    timeout 900 python tools/mfu_probe.py --model resnet50 --norm-dtype bf16 \
      $flags | tee -a "$R/mfu_probe_tpu_r5.jsonl" || RC=1
  done
}

legs_r6() {
  # the full r6 pair set: flagship AND transformer (round >= 7 runs the
  # flagship pair via headline_e2e instead and skips the gpt pair, as
  # the historical r7+ scripts did)
  headline_e2e
  run e2e_sync_gpt  host_overhead_tpu_r6.jsonl 900 BENCH_MODE=e2e BENCH_MODEL=gpt-small BENCH_LOG_STEPS=1 BENCH_TELEMETRY=sync
  run e2e_async_gpt host_overhead_tpu_r6.jsonl 900 BENCH_MODE=e2e BENCH_MODEL=gpt-small BENCH_LOG_STEPS=1 BENCH_TELEMETRY=async
}

legs_r7() {
  # scan-over-layers: TPU compile sweep + deep-model step-time pairs
  # (BENCH_DEPTH marks non-headline variants) + remat-scan memory pairs
  run compile_sweep compile_scan_tpu_r7.jsonl 900 BENCH_MODE=compile
  run deep24_unrolled compile_scan_tpu_r7.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_DEPTH=24 BENCH_BATCH=4
  run deep24_scanned  compile_scan_tpu_r7.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_DEPTH=24 BENCH_BATCH=4 BENCH_SCAN=1
  run deep24_remat_unrolled compile_scan_tpu_r7.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_DEPTH=24 BENCH_BATCH=4 BENCH_REMAT=1
  run deep24_remat_scanned  compile_scan_tpu_r7.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_DEPTH=24 BENCH_BATCH=4 BENCH_REMAT=1 BENCH_SCAN=1
}

legs_r8() {
  # decomposed FSDP (data:1 tunnel -> `degenerate` marker: no
  # collectives to hide; still the schedule+parity probe on Mosaic)
  run overlap_pair overlap_tpu_r8.jsonl 900 BENCH_MODE=overlap
  run lhs_flags_off overlap_tpu_r8.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4
  run lhs_flags_on  overlap_tpu_r8.jsonl 900 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 XLA_FLAGS="$LHS"
}

legs_r9() {
  # compressed DDP comms (data:1 -> degenerate; parity + HLO probe)
  run comms_legs comms_tpu_r9.jsonl 1200 BENCH_MODE=comms
  run ddp_lhs_off comms_tpu_r9.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_DDP_OVERLAP=1
  run ddp_lhs_on  comms_tpu_r9.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_DDP_OVERLAP=1 XLA_FLAGS="$LHS"
}

legs_r10() {
  # decomposed TP (needs model:N>=2 — 1 chip emits the degenerate
  # zero-value record; the lhs A/B fails harmlessly with intent)
  run tp_legs tp_tpu_r10.jsonl 1200 BENCH_MODE=tp
  run tp_lhs_off tp_tpu_r10.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_TP_OVERLAP=1
  run tp_lhs_on  tp_tpu_r10.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_TP_OVERLAP=1 XLA_FLAGS="$LHS"
}

legs_r11() {
  # composed fsdp×tp (needs data:N>=2 × model:M>=2; degenerate at 1)
  run overlap3d_legs overlap3d_tpu_r11.jsonl 1200 BENCH_MODE=overlap3d
  run o3d_lhs_off overlap3d_tpu_r11.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_TP_OVERLAP=1 BENCH_FSDP_OVERLAP=1
  run o3d_lhs_on  overlap3d_tpu_r11.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_TP_OVERLAP=1 BENCH_FSDP_OVERLAP=1 XLA_FLAGS="$LHS"
}

legs_r12() {
  # observability: chip-count-agnostic overhead pair + injected-NaN
  # flight record, plus a real-Mosaic --hlo_report dump
  run obs_legs obs_tpu_r12.jsonl 1200 BENCH_MODE=obs BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_STEPS=20 BENCH_WARMUP=3
  timeout 900 python ddp.py --model gpt-small --scan_layers --max_steps 4 \
    --per_device_train_batch_size 4 --logging_steps 2 --save_steps 0 \
    --dataset_size 512 --hlo_report --anomaly warn --no_resume \
    --output_dir /tmp/obs_hlo_tpu_r12 2>>"$ERR" \
    && cp /tmp/obs_hlo_tpu_r12/hlo_report.json "$R/hlo_report_tpu_r12.json" \
    && echo "hlo report copied to $R/hlo_report_tpu_r12.json" >&2 || RC=1
}

legs_r13() {
  # performance attribution: real MFU (v5e is in PEAK_FLOPS) +
  # mfu_probe cross-check + a named-phase trace through the loop
  run perf_legs perf_tpu_r13.jsonl 1800 BENCH_MODE=perf BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_STEPS=20 BENCH_WARMUP=3 BENCH_LOG_STEPS=5
  timeout 900 python tools/mfu_probe.py --model gpt-small --batch 4 \
    2>>"$ERR" | tee -a "$R/perf_tpu_r13.jsonl" || RC=1
  timeout 900 python ddp.py --model gpt-small --scan_layers --perf_report \
    --profile_steps 6 --max_steps 30 --per_device_train_batch_size 4 \
    --logging_steps 5 --save_steps 0 --dataset_size 2048 --no_resume \
    --output_dir /tmp/perf_trace_tpu_r13 2>>"$ERR" \
    && cp -r /tmp/perf_trace_tpu_r13/profile "$R/perf_trace_tpu_r13_profile" \
    && cp /tmp/perf_trace_tpu_r13/goodput.json "$R/goodput_tpu_r13.json" \
    && echo "trace + goodput copied into $R/" >&2 || RC=1
}

legs_r14() {
  # fleet watchtower: neutrality + endpoints + injected straggler
  # (exchange DEGENERATE on a 1-host tunnel — real rows need
  # launch/run_pod.sh on >= 2 workers; throttle one worker and the
  # verdict should name it with no injection), then a live watchtower
  # run with /status + /metrics scraped next to the records, the
  # perf_baseline restore-compare across two runs of one output_dir,
  # and bench_diff over the fresh legs
  run fleet_legs fleet_tpu_r14.jsonl 1800 BENCH_MODE=fleet BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_STEPS=20 BENCH_WARMUP=3 BENCH_LOG_STEPS=5
  timeout 900 python ddp.py --model gpt-small --scan_layers --perf_report \
    --fleet --status_port 8090 --anomaly warn --max_steps 30 \
    --per_device_train_batch_size 4 --logging_steps 5 --save_steps 0 \
    --dataset_size 2048 --no_resume --output_dir /tmp/fleet_tpu_r14 \
    2>>"$ERR" &
  local train_pid=$!
  sleep 45
  curl -sf http://127.0.0.1:8090/status  > "$R/fleet_status_tpu_r14.json" \
    2>>"$ERR" && echo "status scraped" >&2
  curl -sf http://127.0.0.1:8090/metrics > "$R/fleet_metrics_tpu_r14.prom" \
    2>>"$ERR" && echo "metrics scraped" >&2
  wait "$train_pid" || RC=1
  cp /tmp/fleet_tpu_r14/describe.json "$R/describe_tpu_r14.json" 2>/dev/null \
    && echo "describe.json copied" >&2
  cp /tmp/fleet_tpu_r14/perf_baseline.json "$R/perf_baseline_tpu_r14.json" \
    2>/dev/null && echo "perf_baseline.json copied" >&2
  timeout 900 python ddp.py --model gpt-small --scan_layers --perf_report \
    --fleet --status_port 8090 --anomaly warn --max_steps 60 \
    --per_device_train_batch_size 4 --logging_steps 5 --save_steps 30 \
    --dataset_size 2048 --output_dir /tmp/fleet_tpu_r14 \
    2>&1 | grep -a "perf regression\|goodput summary" >> "$ERR"
  python tools/bench_diff.py "$R" "$R/fleet_tpu_r14.jsonl" --format github \
    > "$R/bench_diff_tpu_r14.md" 2>>"$ERR" \
    || echo "bench_diff flagged drift (see bench_diff_tpu_r14.md)" >&2
}

legs_r15() {
  # memory X-ray: the r15 real-hardware data the CPU record cannot
  # produce — (a) REAL memory_stats watermarks (the CPU record pins the
  # static-degradation path only; on v5e the kind="mem" records carry
  # true per-device bytes-in-use/peak/limit, the remat A/B gains a
  # measured peak delta, and the /metrics HBM gauges export real
  # numbers); (b) a production run whose perf_baseline.json carries a
  # MEASURED peak_hbm_bytes; (c) the restore-compare on real hardware:
  # rerun the same output_dir and attempt 2 should WARN iff the memory
  # footprint drifted out of band (alongside the step-wall signals)
  run mem_legs mem_tpu_r15.jsonl 1800 BENCH_MODE=mem BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_STEPS=20 BENCH_WARMUP=3 BENCH_LOG_STEPS=5
  timeout 900 python ddp.py --model gpt-small --scan_layers --mem_report \
    --perf_report --status_port 8091 --anomaly warn --max_steps 30 \
    --per_device_train_batch_size 4 --logging_steps 5 --save_steps 0 \
    --dataset_size 2048 --no_resume --output_dir /tmp/mem_tpu_r15 \
    2>>"$ERR" &
  local train_pid=$!
  sleep 45
  curl -sf http://127.0.0.1:8091/metrics > "$R/mem_metrics_tpu_r15.prom" \
    2>>"$ERR" && echo "mem /metrics scraped" >&2
  curl -sf http://127.0.0.1:8091/status > "$R/mem_status_tpu_r15.json" \
    2>>"$ERR" && echo "mem /status scraped" >&2
  wait "$train_pid" || RC=1
  cp /tmp/mem_tpu_r15/perf_baseline.json "$R/mem_baseline_tpu_r15.json" \
    2>/dev/null && echo "perf_baseline (peak_hbm stamped) copied" >&2
  timeout 900 python ddp.py --model gpt-small --scan_layers --mem_report \
    --perf_report --max_steps 60 --per_device_train_batch_size 4 \
    --logging_steps 5 --save_steps 30 --dataset_size 2048 \
    --output_dir /tmp/mem_tpu_r15 \
    2>&1 | grep -a "perf regression\|memory budget\|donation audit\|goodput summary" >> "$ERR"
  python tools/bench_diff.py "$R" "$R/mem_tpu_r15.jsonl" --format github \
    > "$R/bench_diff_tpu_r15.md" 2>>"$ERR" \
    || echo "bench_diff flagged drift (see bench_diff_tpu_r15.md)" >&2
}

legs_r16() {
  # pipeline schedules: the r16 real-multi-chip data the 1-core CPU
  # record cannot produce — the CPU host time-slices its 8 virtual
  # devices, so its wall-clock tracks TOTAL work and the lockstep
  # bubble win (zb's whole point) is invisible there. On >= 4 real
  # chips: (a) the gpipe/1f1b/zb step-time triplet at the committed
  # bubble-dominated geometry (small M, the drain bubble dominates) —
  # expect 1f1b ~= gpipe and zb strictly faster, tracking the
  # schedule-model bubble fractions in the record; (b) a deeper-M leg
  # where 1f1b's O(P) activation residency beats gpipe's O(M) on real
  # HBM watermarks (compose with legs_r15's measured watermarks); (c)
  # a bubble-fraction trace leg: --perf_report + --hlo_report on the
  # acceptance config exports tpuddp_perf_bubble_frac and the pipe
  # tripwire on real lowering. Flagged degenerate on < 4 chips.
  run pipe_triplet pipe_tpu_r16.jsonl 2400 BENCH_MODE=pipe BENCH_MICRO=2 BENCH_PIPE=4 BENCH_STEPS=20 BENCH_WARMUP=3
  run pipe_deep_m pipe_tpu_r16.jsonl 2400 BENCH_MODE=pipe BENCH_MICRO=8 BENCH_MICRO_MEM=16 BENCH_PIPE=2 BENCH_STEPS=20 BENCH_WARMUP=3
  timeout 1200 python ddp.py --model gpt-pipe-tiny --scan_layers \
    --pipe_schedule zb --mesh data:2,pipe:2 --perf_report --hlo_report \
    --status_port 8092 --max_steps 30 --per_device_train_batch_size 8 \
    --logging_steps 5 --save_steps 0 --dataset_size 2048 --no_resume \
    --output_dir /tmp/pipe_tpu_r16 2>>"$ERR" &
  local train_pid=$!
  sleep 45
  curl -sf http://127.0.0.1:8092/metrics > "$R/pipe_metrics_tpu_r16.prom" \
    2>>"$ERR" && echo "pipe /metrics scraped (tpuddp_perf_bubble_frac)" >&2
  wait "$train_pid" || RC=1
  cp /tmp/pipe_tpu_r16/hlo_report.json "$R/pipe_hlo_report_tpu_r16.json" \
    2>/dev/null && echo "pipe hlo_report (tripwire clean?) copied" >&2
}

legs_r17() {
  # low-precision compute: the r17 real-hardware data the CPU record
  # cannot produce — the CPU host has no narrow MXU (XLA upcasts the
  # int8/fp8 operands, so the committed quant_cpu_r17.jsonl step ratios
  # price the quantize overhead only; the record carries
  # cpu_no_narrow_mxu=true). On real chips: (a) the full quant legs —
  # on v5e+ expect the int8 step ratio to INVERT (narrow-MXU dots at
  # 2x the bf16 peak, obs/attribution.py PEAK_FLOPS_BY_DTYPE); fp8
  # needs v6e — on earlier generations the fp8 leg measures the e4m3
  # storage/wire win with bf16-rate dots (record it, flag the
  # generation); (b) quantized train legs via the BENCH_QUANT lever
  # (ablation-keyed) incl. the quant × tp composition whose ppermutes
  # carry the narrow ring payloads over real ICI; (c) a production run
  # with --quant_compute int8 --hlo_report --perf_report: the quant
  # tripwire on real Mosaic lowering (narrow dots should appear
  # NATIVELY, not behind converts) + the per-dtype peak rows /
  # quant_peak_headroom in the startup log and perf records.
  run quant_legs quant_tpu_r17.jsonl 2400 BENCH_MODE=quant BENCH_STEPS=20 BENCH_WARMUP=3
  run quant_train_off  quant_tpu_r17.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1
  run quant_train_int8 quant_tpu_r17.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_QUANT=int8
  run quant_train_fp8  quant_tpu_r17.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_QUANT=fp8
  run quant_tp_int8    quant_tpu_r17.jsonl 1200 BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_TP_OVERLAP=1 BENCH_QUANT=int8
  timeout 1200 python ddp.py --model gpt-small --scan_layers \
    --quant_compute int8 --hlo_report --perf_report --max_steps 30 \
    --per_device_train_batch_size 4 --logging_steps 5 --save_steps 0 \
    --dataset_size 2048 --no_resume --output_dir /tmp/quant_tpu_r17 \
    2>>"$ERR" || RC=1
  cp /tmp/quant_tpu_r17/hlo_report.json "$R/quant_hlo_report_tpu_r17.json" \
    2>/dev/null && echo "quant hlo_report (tripwire clean?) copied" >&2
}

legs_r18() {
  # elastic fleet: the BENCH_MODE=elastic legs on real hardware (hot-save
  # step-time overhead pair, the crash->resume MTTR/lost-steps episodes
  # with REAL restore costs — orbax-from-durable vs local-npz hot — and
  # the corrupt-snapshot / torn-durable-step fallbacks), then the real
  # preemption drill a CPU host cannot stage: SIGTERM a hot-snapshotting
  # run mid-flight (graceful checkpoint + clean exit, the r6 stop
  # agreement) and resume on HALF the chips — reshard-on-restore places
  # the surviving shape directly; describe/goodput land in $R as proof.
  run elastic_legs elastic_tpu_r18.jsonl 2400 BENCH_MODE=elastic BENCH_STEPS=20 BENCH_WARMUP=3
  local n half
  n=$(python -c "import jax; print(len(jax.devices()))" 2>>"$ERR") || n=1
  half=$(( n > 1 ? n / 2 : 1 ))
  rm -rf /tmp/elastic_tpu_r18
  timeout 1200 python ddp.py --model gpt-small --scan_layers \
    --mesh "data:$n" --hot_save_steps 5 --save_steps 50 --max_steps 400 \
    --per_device_train_batch_size 4 --logging_steps 5 \
    --dataset_size 4096 --output_dir /tmp/elastic_tpu_r18 2>>"$ERR" &
  local train_pid=$!
  sleep 90
  kill -TERM "$train_pid" 2>/dev/null  # the preemption: checkpoint + exit
  wait "$train_pid"
  timeout 1200 python ddp.py --model gpt-small --scan_layers \
    --mesh "data:$half" --hot_save_steps 5 --save_steps 50 \
    --max_steps 400 --per_device_train_batch_size 4 --logging_steps 5 \
    --dataset_size 4096 --output_dir /tmp/elastic_tpu_r18 \
    2>&1 | grep -a "restored from hot snapshot\|reshard-on-restore\|goodput summary\|perf regression" >> "$ERR" || RC=1
  cp /tmp/elastic_tpu_r18/describe.json "$R/elastic_describe_tpu_r18.json" \
    2>/dev/null && echo "describe.json (resumed on data:$half) copied" >&2
  cp /tmp/elastic_tpu_r18/goodput.json "$R/elastic_goodput_tpu_r18.json" \
    2>/dev/null && echo "goodput.json (attempt 2 accounting) copied" >&2
  python tools/bench_diff.py "$R" "$R/elastic_tpu_r18.jsonl" --format github \
    > "$R/bench_diff_tpu_r18.md" 2>>"$ERR" \
    || echo "bench_diff flagged drift (see bench_diff_tpu_r18.md)" >&2
}

legs_r19() {
  # serving engine: the BENCH_MODE=serve legs on real chips. The CPU
  # record (serve_cpu_r19.jsonl) proves the batching win, the
  # zero-recompile pin and interpret-mode kernel parity; chips are
  # needed for (a) real tokens/sec/chip + TTFT under MXU decode steps,
  # (b) the Mosaic-lowered gather kernel's parity + speed vs the xla
  # gather (PAGED_IMPL=pallas — the record that would flip the default,
  # per the FLASH_BWD/QUANT_IMPL convention), and (c) the int8 KV
  # capacity ablation at hardware dequant cost.
  run serve_xla    serve_tpu_r19.jsonl 1200 BENCH_MODE=serve
  run serve_pallas serve_tpu_r19.jsonl 1200 BENCH_MODE=serve PAGED_IMPL=pallas
  run serve_int8   serve_tpu_r19.jsonl 1200 BENCH_MODE=serve BENCH_KV_QUANT=int8
  python tools/bench_diff.py "$R" "$R/serve_tpu_r19.jsonl" --format github \
    > "$R/bench_diff_tpu_r19.md" 2>>"$ERR" \
    || echo "bench_diff flagged drift (see bench_diff_tpu_r19.md)" >&2
}

legs_r20() {
  # speculative decoding: the BENCH_MODE=spec legs on real chips. The
  # CPU record (spec_cpu_r20.jsonl) proves losslessness, the two-program
  # compile pin and the FLOPs-accounted acceptance win; chips are needed
  # for (a) the real spec-on vs spec-off tokens/sec pair under MXU
  # decode — the memory-bound regime the wager actually targets (each
  # record carries tokens_per_sec_spec/tokens_per_sec_plain from the
  # same run), (b) the acceptance + depth sweep at silicon latency
  # (every invocation appends its depth-ablation rows), and (c) the
  # tpuddp_serve_spec_* gauges scraped from a chip-backed engine
  # (metrics_gauges_live in each record).
  run spec_headline spec_tpu_r20.jsonl 1200 BENCH_MODE=spec
  run spec_k8       spec_tpu_r20.jsonl 1200 BENCH_MODE=spec BENCH_SPEC_K=8
  run spec_fixed_k  spec_tpu_r20.jsonl 1200 BENCH_MODE=spec BENCH_SPEC_DEPTHS=1
  run serve_plain   serve_tpu_r19.jsonl 1200 BENCH_MODE=serve
  python tools/bench_diff.py "$R" "$R/spec_tpu_r20.jsonl" --format github \
    > "$R/bench_diff_tpu_r20.md" 2>>"$ERR" \
    || echo "bench_diff flagged drift (see bench_diff_tpu_r20.md)" >&2
}

legs_r21() {
  # tensor-parallel decode: the BENCH_MODE=serve_tp legs on real chips.
  # The CPU record (serve_tp_cpu_r21.jsonl) proves token-for-token
  # parity, the one-program compile pin and HLO ring evidence; chips
  # are needed for (a) the REAL tp-on vs tp-off tokens/sec pair — on
  # CPU the ring pays ppermute cost for no memory-bandwidth win, on
  # chip the sharded weight reads are the win decode actually wants
  # (each record carries tokens_per_sec_tp/tokens_per_sec_single_replica
  # from the same run), (b) the quantized ring wire at real ICI cost
  # (the ablation row rides every invocation), and (c) the
  # tpuddp_serve_tp_* gauges scraped from a chip-backed engine.
  run serve_tp_pair serve_tp_tpu_r21.jsonl 1200 BENCH_MODE=serve_tp
  run serve_tp_4way serve_tp_tpu_r21.jsonl 1200 BENCH_MODE=serve_tp \
    BENCH_SERVE_TP=4 BENCH_SERVE_TP_SLOTS=8
  run serve_plain   serve_tpu_r19.jsonl 1200 BENCH_MODE=serve
  python tools/bench_diff.py "$R" "$R/serve_tp_tpu_r21.jsonl" --format github \
    > "$R/bench_diff_tpu_r21.md" 2>>"$ERR" \
    || echo "bench_diff flagged drift (see bench_diff_tpu_r21.md)" >&2
}

legs_r22() {
  # 4D composition: the BENCH_MODE=pipe_compose legs on real chips. The
  # CPU record (pipe_compose_cpu_r22.jsonl) proves pipe x tp / pipe x ddp
  # parity vs sequential stages and the branch-collective-free slot
  # body; chips are needed for (a) the LOCKSTEP step ratios -- on the
  # 1-core CPU the boundary waves serialise as extra work, on chips the
  # tp psums and masked ddp reduces overlap under adjacent microbatch
  # compute the way the makespan model predicts (each leg carries
  # step_time_plain/composed from the same mesh), (b) the pipe x tp
  # geometry at a real model axis (data x model:2 x pipe:2 needs 8
  # chips; BENCH_MICRO sweeps the bubble down), and (c) ICI-priced
  # wire_bytes_pipe/model attribution from --perf_report on a composed
  # run. A 1-chip tunnel can only re-prove the CPU story -- both multi-
  # chip legs below degrade to degenerate records there.
  run pipe_compose_m4 pipe_compose_tpu_r22.jsonl 1800 BENCH_MODE=pipe_compose
  run pipe_compose_m8 pipe_compose_tpu_r22.jsonl 1800 BENCH_MODE=pipe_compose \
    BENCH_MICRO=8
  python tools/bench_diff.py "$R" "$R/pipe_compose_tpu_r22.jsonl" --format github \
    > "$R/bench_diff_tpu_r22.md" 2>>"$ERR" \
    || echo "bench_diff flagged drift (see bench_diff_tpu_r22.md)" >&2
}

# -- the historical chain ---------------------------------------------------
if [ "$ROUND" -eq 4 ]; then
  legs_r4
elif [ "$ROUND" -eq 5 ]; then
  # the historical r5 poller ran the deferred r4 suite first
  legs_r4; legs_r5
elif [ "$ROUND" -eq 6 ]; then
  legs_r6; legs_r4; legs_r5
else
  headline_e2e
  legs_r7
  legs_r4
  legs_r5
  r=8
  while [ "$r" -le "$ROUND" ]; do
    "legs_r$r"
    r=$((r + 1))
  done
fi

echo "done; round-$ROUND records in $R/ (see the legs_r$ROUND function for filenames)" >&2
exit $RC
