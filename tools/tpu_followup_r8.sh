#!/usr/bin/env bash
# Round-8 TPU measurement suite. Ordering per the established pattern:
# (1) the r7 backlog FIRST (tools/tpu_followup_r7.sh — itself headed by the
# still-open r6 e2e host-overhead headline pair, then the r7 scan-over-
# layers compile/step-time legs, then r4/r5), then (2) the round-8
# decomposed-FSDP overlap legs on the real chip. Note: the current tunnel
# exposes ONE v5e chip — at data:1 the overlap record is marked
# `degenerate` (no collectives to hide) and serves as the schedule+parity
# probe on real hardware; the step-time WIN case needs a multi-chip slice
# and stays flagged for the next topology change. The latency-hiding
# scheduler flag pack is exercised via a paired train run (flags off/on).
# Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r8.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, outfile, env... — logs one JSON line or the error
  local name=$1 out=$2; shift 2
  echo "=== $name ===" >&2
  env "$@" timeout 900 python bench.py 2>>"$R/.followup_r8.err" | tee -a "$R/$out"
}

# 1. the r7 backlog first (r6 e2e headline pair -> r7 legs -> r4/r5)
bash tools/tpu_followup_r7.sh
rc7=$?

# 2. round-8 overlap legs
#    (a) BENCH_MODE=overlap on the chip: bit-parity + HLO schedule
#        evidence + memory legs against the TPU compiler (degenerate
#        step-time at data:1; still the first real-Mosaic record)
run overlap_pair overlap_tpu_r8.jsonl BENCH_MODE=overlap
#    (b) the latency-hiding-scheduler flag pack A/B on the flagship
#        config: same train-mode bench with and without the pack — the
#        XLA_FLAGS half of the overlap story, meaningful even at 1 chip
#        (async collectives also overlap H2D/D2H and infeed)
run lhs_flags_off overlap_tpu_r8.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4
run lhs_flags_on  overlap_tpu_r8.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 \
    XLA_FLAGS="--xla_tpu_enable_latency_hiding_scheduler=true --xla_tpu_enable_async_collective_fusion=true --xla_tpu_enable_async_collective_fusion_fuse_all_gather=true --xla_tpu_enable_async_collective_fusion_multiple_steps=true --xla_tpu_overlap_compute_collective_tc=true --xla_enable_async_all_gather=true"

echo "done; r8 records in $R/overlap_tpu_r8.jsonl" >&2
exit $rc7
