#!/usr/bin/env bash
# Round-5 TPU measurement suite: the gpt-long fused-stack story
# (VERDICT r4 next-step #3) plus a fresh flagship number. Run AFTER
# tools/tpu_followup_r4.sh (which re-records flash incl. both backward
# impls, bert-base under the dispatch policy, and TPU e2e).
# Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r5.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, env..., — logs one JSON line or the error
  local name=$1; shift
  echo "=== $name ===" >&2
  env "$@" timeout 900 python bench.py 2>>"$R/.followup_r5.err" | tee -a "$R/train_tpu_r5.jsonl"
}

# 1. the long-context flagship composition the blockwise head + flash +
#    remat exist for: throughput, MFU, and the executable's own memory
#    breakdown (temp_mb), each lever ablated against its baseline
run gpt_long_fused   BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10
run gpt_long_dense   BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10 BENCH_DENSE_HEAD=1
run gpt_long_noflash BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10 FLASH_DISABLE=1
run gpt_long_dense_noflash BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10 BENCH_DENSE_HEAD=1 FLASH_DISABLE=1

# 2. flash backward on real Mosaic, pinned pallas, long-context shape —
#    the Mosaic compile + parity + timing record that decides FLASH_BWD's
#    hardware default (r4's flash mode re-records 512-4096; this adds the
#    bwd-heavy batch-4 case)
run flash4096_b4 BENCH_MODE=flash BENCH_SEQ=4096

# 3. fresh flagship ladder numbers for BENCH_r05 context (bf16-BN resnet50
#    is the headline; gpt-small exercises the new bwd default on hardware)
run resnet50  BENCH_MODE=train BENCH_MODEL=resnet50
run gpt_small BENCH_MODE=train BENCH_MODEL=gpt-small

# 4. the resnet50 MFU lever the roofline analysis names (selective remat:
#    save conv outputs, recompute norm/ReLU) — probe all three schedules
for flags in "" "--remat" "--remat --save-convs"; do
  echo "=== mfu_probe resnet50 $flags ===" >&2
  timeout 900 python tools/mfu_probe.py --model resnet50 --norm-dtype bf16 \
    $flags | tee -a "$R/mfu_probe_tpu_r5.jsonl"
done

echo "done; records in $R/train_tpu_r5.jsonl + mfu_probe_tpu_r5.jsonl" >&2
