#!/usr/bin/env bash
# Round-12 tunnel poller: probe the axon relay port every 60s; when it
# answers twice in a row (10s apart), run the round-12 suite once and
# exit. The r12 suite chains the r11 backlog FIRST (which itself leads
# with the r10/r9/r8/r7 chains and the r6 e2e headline pair), then
# records the observability legs — the health-pack+sentry overhead pair
# against real TPU step times, the injected-NaN flight-record proof,
# and a Mosaic-compiled --hlo_report dump (all chip-count-agnostic,
# unlike the overlap modes). Gives up after ~11 h.
set -u
cd "$(dirname "$0")/.."
probe() { timeout 2 bash -c '</dev/tcp/127.0.0.1/8082' 2>/dev/null; }
deadline=$(( $(date +%s) + 39600 ))
while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    sleep 10
    if probe; then
      echo "tunnel up at $(date -u +%FT%TZ); running r12 followup suite" >&2
      bash tools/tpu_followup_r12.sh
      exit $?
    fi
  fi
  sleep 60
done
echo "poller gave up: tunnel never answered" >&2
exit 3
