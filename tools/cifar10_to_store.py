"""Convert CIFAR-10 binary batches into a memory-mapped array store.

Parses the canonical *binary version* of CIFAR-10 directly — no torch, no
tfds, no pickle: each record is 1 label byte followed by 3072 bytes of
32x32 RGB in channel-planar order (1024 R, 1024 G, 1024 B). Output is a
``StoreWriter`` store (uint8 NHWC ``image`` + int32 ``label``) that
``--data_dir`` / ``--eval_data_dir`` consume, with normalisation and
augmentation running on device (``models/task.py``). The reference's data
layer only ever materialised ``torch.randn`` (``/root/reference/
dataset.py:10-11``); this is the real-data rung it never had.

Usage (with the corpus from https://www.cs.toronto.edu/~kriz/cifar.html)::

    python tools/cifar10_to_store.py --src cifar-10-batches-bin \
        --out /data/cifar10_train                       # data_batch_[1-5]
    python tools/cifar10_to_store.py --src cifar-10-batches-bin \
        --out /data/cifar10_test --split test           # test_batch.bin
    python ddp.py --model resnet18 --data_dir /data/cifar10_train \
        --eval_data_dir /data/cifar10_test --augment crop-flip --bf16 ...

Offline environments: ``--fabricate N`` writes a *learnable* stand-in
corpus in the exact same binary format (class-conditional patterns + pixel
noise), so the full parse → store → train → eval pipeline is exercisable
and a trained model's eval accuracy is meaningfully above chance. The
record format, not the images, is what this tool owns.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RECORD_BYTES = 1 + 32 * 32 * 3  # label byte + channel-planar RGB
TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
TEST_FILES = ["test_batch.bin"]


def parse_batch_file(path: Path) -> tuple[np.ndarray, np.ndarray]:
    """One binary batch file → ``(images_NHWC_uint8, labels_int32)``."""
    raw = np.frombuffer(path.read_bytes(), dtype=np.uint8)
    if raw.size == 0 or raw.size % RECORD_BYTES:
        raise ValueError(
            f"{path}: {raw.size} bytes is not a multiple of the "
            f"{RECORD_BYTES}-byte CIFAR-10 record (1 label + 3072 pixels)"
        )
    records = raw.reshape(-1, RECORD_BYTES)
    labels = records[:, 0].astype(np.int32)
    if labels.max(initial=0) > 9:
        raise ValueError(
            f"{path}: label {labels.max()} > 9 — not CIFAR-10 binary "
            "(CIFAR-100 records carry 2 label bytes)"
        )
    # planar (3, 32, 32) → NHWC
    images = records[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(images), labels


def convert(src: Path, out: Path, files: list[str]) -> int:
    from pytorch_ddp_template_tpu.data.filestore import StoreWriter

    missing = [f for f in files if not (src / f).is_file()]
    if missing:
        raise FileNotFoundError(
            f"{src} lacks {missing}; expected the extracted "
            "cifar-10-batches-bin directory"
        )
    n = 0
    with StoreWriter(out) as w:
        for name in files:
            images, labels = parse_batch_file(src / name)
            w.append({"image": images, "label": labels})
            n += len(labels)
    return n


def fabricate(src: Path, samples: int, seed: int) -> None:
    """Write a learnable stand-in corpus in CIFAR-10 binary format.

    Each class gets a fixed random 32x32x3 prototype; samples are the
    prototype + heavy pixel noise, so a conv net separates the classes but
    nothing is trivially constant. Written as the standard 5-train-batch +
    1-test-batch file layout so ``convert`` exercises the real parser.
    """
    rng = np.random.default_rng(seed)
    protos = rng.integers(32, 224, (10, 32, 32, 3)).astype(np.int16)

    def records(count: int) -> bytes:
        labels = rng.integers(0, 10, count)
        noise = rng.integers(-80, 81, (count, 32, 32, 3))
        imgs = np.clip(protos[labels] + noise, 0, 255).astype(np.uint8)
        planar = imgs.transpose(0, 3, 1, 2).reshape(count, -1)
        out = np.empty((count, RECORD_BYTES), np.uint8)
        out[:, 0] = labels
        out[:, 1:] = planar
        return out.tobytes()

    src.mkdir(parents=True, exist_ok=True)
    per = max(1, samples // 5)
    for name in TRAIN_FILES:
        (src / name).write_bytes(records(per))
    (src / TEST_FILES[0]).write_bytes(records(max(1, samples // 5)))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--src", required=True,
                   help="directory holding the CIFAR-10 binary batch files")
    p.add_argument("--out", required=True, help="store directory to write")
    p.add_argument("--split", choices=["train", "test"], default="train")
    p.add_argument("--fabricate", type=int, default=0, metavar="N",
                   help="first write a learnable stand-in corpus of ~N train "
                        "samples in CIFAR-10 binary format into --src "
                        "(offline environments; see module docstring)")
    p.add_argument("--seed", type=int, default=0, help="for --fabricate")
    args = p.parse_args(argv)

    src, out = Path(args.src), Path(args.out)
    if args.fabricate:
        fabricate(src, args.fabricate, args.seed)
        print(f"fabricated stand-in corpus under {src}")
    t0 = time.perf_counter()
    files = TRAIN_FILES if args.split == "train" else TEST_FILES
    n = convert(src, out, files)
    total = sum(f.stat().st_size for f in out.glob("*.bin"))
    print(f"wrote {n} samples ({total / 1e6:.1f} MB) to {out} "
          f"in {time.perf_counter() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
