#!/usr/bin/env bash
# Round-5 tunnel poller: probe the axon relay port every 60s; when it answers
# twice in a row (10s apart), run the deferred round-4 TPU suite once and exit.
# Gives up after ~11 h.
set -u
cd "$(dirname "$0")/.."
probe() { timeout 2 bash -c '</dev/tcp/127.0.0.1/8082' 2>/dev/null; }
deadline=$(( $(date +%s) + 39600 ))
while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    sleep 10
    if probe; then
      echo "tunnel up at $(date -u +%FT%TZ); running followup suites" >&2
      bash tools/tpu_followup_r4.sh
      rc4=$?
      bash tools/tpu_followup_r5.sh
      rc5=$?
      exit $(( rc4 > rc5 ? rc4 : rc5 ))
    fi
  fi
  sleep 60
done
echo "poller gave up: tunnel never answered" >&2
exit 3
