#!/usr/bin/env bash
# Round-4 TPU follow-up suite: runs the measurements that were blocked by
# the tunnel outage. Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r4.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, env..., — logs one JSON line or the error
  local name=$1; shift
  echo "=== $name ===" >&2
  env "$@" timeout 900 python bench.py 2>>"$R/.followup.err" | tee -a "$R/followup_tpu_r4.jsonl"
}

# 1. flash at seq 512: decides whether FLASH_MIN_SEQ can drop to 512
#    (bert-base regime; policy currently routes 512 to XLA, unmeasured)
run flash512 BENCH_MODE=flash BENCH_SEQ=512

# 1b. re-record flash at 1024/2048/4096: the mode now also times the
#     Pallas backward kernels (bwd_* columns), absent from flash_tpu_r4
run flash1024 BENCH_MODE=flash BENCH_SEQ=1024
run flash2048 BENCH_MODE=flash BENCH_SEQ=2048
run flash4096 BENCH_MODE=flash BENCH_SEQ=4096

# 2. bert-base train under the current dispatch policy (XLA at 512) —
#    compare with the pre-policy record 208.08 seq/s (train_tpu_r4.jsonl)
run bert BENCH_MODE=train BENCH_MODEL=bert-base

# 3. e2e vs cached-batch on the flagship: quantify the input path on TPU
run e2e_rn50 BENCH_MODE=e2e BENCH_MODEL=resnet50

# 4. long-context single chip: gpt-long trains with flash at 4096 in situ
run gpt_long BENCH_MODE=train BENCH_MODEL=gpt-long BENCH_BATCH=1 BENCH_STEPS=10

# 5. gpt-small re-measure: its seq-1024 training step now runs the Pallas
#    flash BACKWARD kernels too (record to compare vs 91.9 seq/s pre-bwd)
run gpt_small BENCH_MODE=train BENCH_MODEL=gpt-small

# 5b. blockwise LM head ablation on hardware: throughput with/without the
#     (B,T,V) logits tensor (memory win is proven; is there a time cost?)
run gpt_small_fused BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_FUSED_HEAD=1
run bert_fused BENCH_MODE=train BENCH_MODEL=bert-base BENCH_FUSED_HEAD=1

# 6. transformer MFU decomposition on TPU-compiled HLO (the CPU probe is
#    unrepresentative here: different fusion, dense attention matrices)
echo "=== mfu_probe bert-base ===" >&2
timeout 900 python tools/mfu_probe.py --model bert-base --iters 10 \
  | tee -a "$R/mfu_probe_bert_tpu_r4.jsonl"

echo "done; records in $R/followup_tpu_r4.jsonl" >&2
