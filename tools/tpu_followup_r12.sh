#!/usr/bin/env bash
# Round-12 TPU measurement suite. Ordering per the established pattern:
# (1) the r11 backlog FIRST (tools/tpu_followup_r11.sh — itself chaining
# r10/r9/r8/r7, headed by the still-open r6 e2e host-overhead headline
# pair and the composed-schedule legs that need a multi-chip slice),
# then (2) the round-12 observability legs on the real chip.
# The obs legs are chip-count-agnostic: the overhead pair and the
# injected-NaN flight-record proof run fine on ONE chip (unlike the
# overlap modes) — the real-hardware datum here is the health-pack +
# per-step-sentry cost against real TPU step times, where the device-
# bound step dwarfs the host-side queue work far more than the noisy
# CPU bench host does. The --hlo_report dump on the chip additionally
# records what the Mosaic compiler's HLO looks like to the walkers.
# Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r12.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, outfile, env... — logs one JSON line or the error
  local name=$1 out=$2; shift 2
  echo "=== $name ===" >&2
  env "$@" timeout 1200 python bench.py 2>>"$R/.followup_r12.err" | tee -a "$R/$out"
}

# 1. the r11 backlog first (r10/r9/r8/r7 chain -> composed-schedule legs)
bash tools/tpu_followup_r11.sh
rc11=$?

# 2. round-12 observability legs
#    (a) BENCH_MODE=obs on the chip: the health-pack+sentry overhead
#        ratio against real device-bound steps (gpt-small — a compute-
#        heavy step, so the pack's param-sized reductions are properly
#        dwarfed) + the injected-NaN flight-record completeness proof
run obs_legs obs_tpu_r12.jsonl BENCH_MODE=obs BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_STEPS=20 BENCH_WARMUP=3
#    (b) a real-TPU --hlo_report dump: the startup schedule report from
#        the Mosaic-compiled train step (scan-over-layers so the walkers
#        see the scanned body). The report lands in the run's output dir;
#        copy it next to the records for the round's evidence.
timeout 900 python ddp.py --model gpt-small --scan_layers --max_steps 4 \
  --per_device_train_batch_size 4 --logging_steps 2 --save_steps 0 \
  --dataset_size 512 --hlo_report --anomaly warn --no_resume \
  --output_dir /tmp/obs_hlo_tpu_r12 2>>"$R/.followup_r12.err" \
  && cp /tmp/obs_hlo_tpu_r12/hlo_report.json "$R/hlo_report_tpu_r12.json" \
  && echo "hlo report copied to $R/hlo_report_tpu_r12.json" >&2

echo "done; r12 records in $R/obs_tpu_r12.jsonl" >&2
exit $rc11
