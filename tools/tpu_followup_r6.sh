#!/usr/bin/env bash
# Round-6 TPU measurement suite. Per the round-5 verdict's "headline number
# first" directive: the FIRST thing a fresh tunnel window records is the
# BENCH_MODE=e2e before/after pair for the host-sync-free hot loop
# (telemetry sync vs async, same model/batch/steps — host_overhead_pct is
# the datum), THEN the deferred r4/r5 suites run. Safe to re-run; each mode
# appends one JSON line.
# Usage: bash tools/tpu_followup_r6.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, env..., — logs one JSON line or the error
  local name=$1; shift
  echo "=== $name ===" >&2
  env "$@" timeout 900 python bench.py 2>>"$R/.followup_r6.err" | tee -a "$R/host_overhead_tpu_r6.jsonl"
}

# 1. HEADLINE FIRST: the e2e host-overhead pair on the flagship config.
#    sync = the pre-change loop (inline float conversions at every logging
#    interval); async = device arrays drained off-thread + bounded
#    dispatch-depth barrier. host_overhead_pct(sync) - host_overhead_pct(async)
#    is the hot-loop win on real hardware.
run e2e_sync  BENCH_MODE=e2e BENCH_MODEL=resnet50 BENCH_LOG_STEPS=1 BENCH_TELEMETRY=sync
run e2e_async BENCH_MODE=e2e BENCH_MODEL=resnet50 BENCH_LOG_STEPS=1 BENCH_TELEMETRY=async

# 2. same pair on the transformer flagship (larger step: the overlap win
#    is proportionally smaller but the dispatch-pipeline protection shows
#    in p99, which the full-loop leg logs via StepTimer)
run e2e_sync_gpt  BENCH_MODE=e2e BENCH_MODEL=gpt-small BENCH_LOG_STEPS=1 BENCH_TELEMETRY=sync
run e2e_async_gpt BENCH_MODE=e2e BENCH_MODEL=gpt-small BENCH_LOG_STEPS=1 BENCH_TELEMETRY=async

# 3. then the deferred round-4/5 backlogs, unchanged
bash tools/tpu_followup_r4.sh
rc4=$?
bash tools/tpu_followup_r5.sh
rc5=$?

echo "done; r6 records in $R/host_overhead_tpu_r6.jsonl" >&2
exit $(( rc4 > rc5 ? rc4 : rc5 ))
