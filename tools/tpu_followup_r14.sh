#!/usr/bin/env bash
# Round-14 TPU measurement suite. Ordering per the established pattern:
# (1) the r13 backlog FIRST (tools/tpu_followup_r13.sh — itself chaining
# r12/r11/r10/r9/r8/r7, headed by the still-open r6 e2e host-overhead
# headline pair), then (2) the round-14 fleet-watchtower legs on the
# real chip. The r14 real-hardware data this CPU host cannot produce:
# (a) a REAL multi-host fleet exchange — the CPU record's allgather is
# skipped by construction (one process), so the wire path of
# obs/fleet.py (jax.experimental.multihost_utils.process_allgather on
# the telemetry drain thread) only exercises on a multi-host pod; run
# the BENCH_MODE=fleet leg under launch/run_pod.sh on >= 2 workers and
# the fleet table gains real per-host rows (single-host tunnel: the leg
# below is DEGENERATE on the exchange — still valid for overhead +
# endpoints + the injected-straggler bundle);
# (b) real straggler attribution — on a pod, throttle one worker (e.g.
# `nice -n 19` its process or pin it to fewer cores) and the verdict
# should name THAT host with no injection;
# (c) a REAL perf_baseline restore-compare — rerun the same output_dir
# across two tunnel sessions and the second attempt should WARN iff the
# chip/mesh/wheel changed the steady step wall by > --regression_pct.
# Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r14.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, outfile, env... — logs one JSON line or the error
  local name=$1 out=$2; shift 2
  echo "=== $name ===" >&2
  env "$@" timeout 1800 python bench.py 2>>"$R/.followup_r14.err" | tee -a "$R/$out"
}

# 1. the r13 backlog first (r12/r11/r10/r9/r8/r7 chain -> perf legs)
bash tools/tpu_followup_r13.sh
rc13=$?

# 2. round-14 fleet-watchtower legs
#    (a) BENCH_MODE=fleet on the chip: neutrality pair against real
#        device-bound steps + live endpoint scrape + injected-straggler
#        bundle (exchange DEGENERATE on a 1-host tunnel — flagged by
#        the record's n_processes field)
run fleet_legs fleet_tpu_r14.jsonl BENCH_MODE=fleet BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_STEPS=20 BENCH_WARMUP=3 BENCH_LOG_STEPS=5
#    (b) a real production run with the full watchtower on — fleet +
#        status endpoint + anomaly sentry + perf report — and a scrape
#        of /status and /metrics copied next to the records
timeout 900 python ddp.py --model gpt-small --scan_layers --perf_report \
  --fleet --status_port 8090 --anomaly warn --max_steps 30 \
  --per_device_train_batch_size 4 --logging_steps 5 --save_steps 0 \
  --dataset_size 2048 --no_resume --output_dir /tmp/fleet_tpu_r14 \
  2>>"$R/.followup_r14.err" &
train_pid=$!
sleep 45
curl -sf http://127.0.0.1:8090/status  > "$R/fleet_status_tpu_r14.json" \
  2>>"$R/.followup_r14.err" && echo "status scraped" >&2
curl -sf http://127.0.0.1:8090/metrics > "$R/fleet_metrics_tpu_r14.prom" \
  2>>"$R/.followup_r14.err" && echo "metrics scraped" >&2
wait "$train_pid"
cp /tmp/fleet_tpu_r14/describe.json "$R/describe_tpu_r14.json" 2>/dev/null \
  && echo "describe.json copied" >&2
cp /tmp/fleet_tpu_r14/perf_baseline.json "$R/perf_baseline_tpu_r14.json" \
  2>/dev/null && echo "perf_baseline.json copied" >&2
#    (c) the restore-compare tripwire: rerun the SAME output_dir with a
#        larger budget; attempt 2 compares against (b)'s baseline and
#        WARNs iff the steady step wall drifted out of band
timeout 900 python ddp.py --model gpt-small --scan_layers --perf_report \
  --fleet --status_port 8090 --anomaly warn --max_steps 60 \
  --per_device_train_batch_size 4 --logging_steps 5 --save_steps 30 \
  --dataset_size 2048 --output_dir /tmp/fleet_tpu_r14 \
  2>&1 | grep -a "perf regression\|goodput summary" >> "$R/.followup_r14.err"
#    (d) the committed records as tripwires against the fresh TPU legs
python tools/bench_diff.py "$R" "$R/fleet_tpu_r14.jsonl" --format github \
  > "$R/bench_diff_tpu_r14.md" 2>>"$R/.followup_r14.err" \
  || echo "bench_diff flagged drift (see bench_diff_tpu_r14.md)" >&2

echo "done; r14 records in $R/fleet_tpu_r14.jsonl" >&2
exit $rc13
