#!/usr/bin/env bash
# Thin shim (r15 consolidation): the per-round followup scripts now live
# as one parameterized suite — tools/tpu_followup.sh <round> — with this
# spelling kept so committed docs/BENCH.md commands keep working. The
# round-14 legs (and the historical backlog chain before them) run
# unchanged; see the legs_r14 function there.
exec bash "$(dirname "$0")/tpu_followup.sh" 14
