"""Cross-process performance probe: train throughput + collectives over a
real two-process rendezvous (VERDICT r4 next-step #7).

Single-chip environments cannot measure 1→N-chip scaling; what they CAN
measure is the cross-process SPMD path itself — `jax.distributed`
rendezvous, a mesh spanning two OS processes (the DCN boundary all
multi-host code rides), sharded loading, and timed train steps +
collectives across it. This probe records:

- ``twoproc_train_steps_per_sec`` — steps/sec of the jitted train step on
  a 2-process 8-device CPU mesh, with the single-process same-mesh number
  and their ratio alongside;
- ``twoproc_psum_ms`` / ``twoproc_all_gather_ms`` — cross-process
  collective latencies at 1 MiB.

Caveat recorded in every line: on a 1-core host the two processes share
the core, so the ratio measures contention + rendezvous overhead, not
scaling (which needs real chips; BENCH_MODE=scaling is the hardware
harness).

Usage: python tools/twoproc_bench.py [--steps 20] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def worker(role: str, coord: str, steps: int, outdir: Path) -> int:
    """Measurement body. role: "single" (one process, 8 devices) or
    "0"/"1" (two processes, 4 local devices each)."""
    sys.path.insert(0, str(REPO))
    os.environ["JAX_PLATFORMS"] = "cpu"
    n_local = 8 if role == "single" else 4
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_local}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax import lax, shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.data.loader import ShardedLoader
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    cfg = TrainingConfig(
        cpu=True, mesh="data:8", per_device_train_batch_size=32,
        dataset_size=4096, seed=0, warmup_steps=0,
        coordinator_address=None if role == "single" else coord,
        num_processes=None if role == "single" else 2,
        process_id=None if role == "single" else int(role),
    )
    ctx = init(cfg)
    task, ds = build("mlp-wide", cfg)
    loader = ShardedLoader(ds, ctx.mesh, cfg.train_batch_size, seed=0)
    tx, schedule = make_optimizer(cfg, total_steps=10_000)
    batches = iter(loader.epoch(0))
    first = next(batches)
    params, extra = task.init(ctx.seed_key, first)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       extra_vars=extra, opt_state=tx.init(params),
                       rng=jax.random.clone(ctx.seed_key))
    from pytorch_ddp_template_tpu.parallel import shard_tree

    state = shard_tree(state, ctx.mesh)
    step_fn = make_train_step(task, tx, schedule)

    # warmup (compile) then timed steps on a recycled batch — the input
    # path is not what this probe measures
    state, m = step_fn(state, first)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step_fn(state, first)
    jax.block_until_ready(m["loss"])
    steps_per_sec = steps / (time.perf_counter() - t0)

    # cross-process collectives at 1 MiB f32
    n_elem = (1 << 20) // 4
    sharding = NamedSharding(ctx.mesh, P("data"))
    x = jax.device_put(
        jnp.arange(n_elem * 8, dtype=jnp.float32).reshape(8, n_elem),
        sharding)

    def timed(fn):
        y = fn(x)
        jax.block_until_ready(y)
        t0 = time.perf_counter()
        for _ in range(10):
            y = fn(x)
        jax.block_until_ready(y)
        return (time.perf_counter() - t0) / 10 * 1e3  # ms

    psum_fn = jax.jit(shard_map(
        lambda a: lax.psum(a, "data"), mesh=ctx.mesh,
        in_specs=P("data"), out_specs=P(), check_vma=False))
    ag_fn = jax.jit(shard_map(
        lambda a: lax.all_gather(a, "data", tiled=True), mesh=ctx.mesh,
        in_specs=P("data"), out_specs=P(), check_vma=False))
    psum_ms, ag_ms = timed(psum_fn), timed(ag_fn)

    if role in ("single", "0"):
        name = "single" if role == "single" else "twoproc"
        (outdir / f"{name}.json").write_text(json.dumps({
            "steps_per_sec": steps_per_sec,
            "psum_1mib_ms": round(psum_ms, 3),
            "all_gather_1mib_ms": round(ag_ms, 3),
            "process_count": jax.process_count(),
            "global_devices": jax.device_count(),
            "loss": float(np.asarray(m["loss"])),
        }))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--out", default=str(REPO / "bench_records" /
                                         "twoproc_cpu_r5.jsonl"))
    ap.add_argument("--worker", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--coord", default="", help=argparse.SUPPRESS)
    ap.add_argument("--workdir", default="", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.worker is not None:
        return worker(args.worker, args.coord, args.steps, Path(args.workdir))

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        workdir = Path(td)
        env = dict(os.environ, PYTHONPATH=str(REPO))

        def run(roles: list[str], coord: str) -> None:
            procs = [
                subprocess.Popen(
                    [sys.executable, __file__, "--worker", r, "--coord",
                     coord, "--workdir", str(workdir),
                     "--steps", str(args.steps)],
                    env=env, cwd=REPO)
                for r in roles
            ]
            try:
                for p in procs:
                    if p.wait(timeout=600):
                        raise RuntimeError(
                            f"worker failed: rc={p.returncode}")
            finally:
                # a crashed/timed-out worker must not orphan its sibling
                # blocked in the jax.distributed rendezvous
                for p in procs:
                    if p.poll() is None:
                        p.kill()

        run(["single"], "")
        run(["0", "1"], f"127.0.0.1:{_free_port()}")

        single = json.loads((workdir / "single.json").read_text())
        two = json.loads((workdir / "twoproc.json").read_text())

    ratio = two["steps_per_sec"] / max(single["steps_per_sec"], 1e-9)
    n_cores = os.cpu_count() or 1
    record = {
        "metric": "twoproc_train_steps_per_sec",
        "value": round(two["steps_per_sec"], 3),
        "unit": "steps/sec",
        "single_process_steps_per_sec": round(single["steps_per_sec"], 3),
        "ratio_vs_single": round(ratio, 3),
        "twoproc_psum_1mib_ms": two["psum_1mib_ms"],
        "twoproc_all_gather_1mib_ms": two["all_gather_1mib_ms"],
        "single_psum_1mib_ms": single["psum_1mib_ms"],
        "single_all_gather_1mib_ms": single["all_gather_1mib_ms"],
        "host_cores": n_cores,
        "note": ("2 processes x 4 virtual CPU devices vs 1 process x 8, "
                 "same global batch; on a shared-core host the ratio "
                 "measures contention + DCN-boundary overhead, not chip "
                 "scaling"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    with out.open("a") as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record))
    # sane band: cross-process must neither collapse (a rendezvous/DCN
    # pathology would push the ratio toward 0) nor exceed the physical
    # envelope. Generous bounds — the host may be contended.
    if not 0.05 <= ratio <= 3.0:
        raise AssertionError(
            f"two-process throughput ratio {ratio:.3f} outside sane band "
            "[0.05, 3.0] — cross-process path pathology?"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
