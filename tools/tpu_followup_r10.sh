#!/usr/bin/env bash
# Round-10 TPU measurement suite. Ordering per the established pattern:
# (1) the r9 backlog FIRST (tools/tpu_followup_r9.sh — itself chaining the
# r8/r7 backlogs, headed by the still-open r6 e2e host-overhead headline
# pair), then (2) the round-10 decomposed-TP legs on the real chip.
# Note: the current tunnel exposes ONE v5e chip — BENCH_MODE=tp needs a
# model:N>=2 mesh axis, so a single-chip run emits a `degenerate`
# zero-value record (there is no TP collective to decompose, not even a
# parity probe; the r8 convention). The real legs — ring-vs-GSPMD parity
# on the Mosaic compiler, step-time ratio with actual ICI latency to
# hide, and the ppermute schedule under the latency-hiding pack — stay
# flagged for the next multi-chip tunnel window.
# Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r10.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, outfile, env... — logs one JSON line or the error
  local name=$1 out=$2; shift 2
  echo "=== $name ===" >&2
  env "$@" timeout 1200 python bench.py 2>>"$R/.followup_r10.err" | tee -a "$R/$out"
}

# 1. the r9 backlog first (r8/r7 chain -> r9 comms legs)
bash tools/tpu_followup_r9.sh
rc9=$?

# 2. round-10 decomposed-TP legs
#    (a) BENCH_MODE=tp on the chip: degenerate marker at 1 chip; on a
#        multi-chip slice this is the real record — default-vs-ring
#        parity, fwd/bwd ppermute schedule evidence from the Mosaic
#        compiler, the never-materialised-logits live range, and the
#        step-time ratio with real ICI latency under the dots
run tp_legs tp_tpu_r10.jsonl BENCH_MODE=tp
#    (b) the latency-hiding-scheduler pack A/B over the decomposed-TP
#        train step (multi-chip only — gpt-small heads/mlp divide
#        model:2): whether the scheduler actually runs the single-hop
#        ppermutes under the partial dots on real hardware. Harmless
#        degenerate-config failure at 1 chip (refused with intent).
run tp_lhs_off tp_tpu_r10.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_TP_OVERLAP=1
run tp_lhs_on  tp_tpu_r10.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_TP_OVERLAP=1 \
    XLA_FLAGS="--xla_tpu_enable_latency_hiding_scheduler=true --xla_tpu_enable_async_collective_fusion=true --xla_tpu_enable_async_collective_fusion_fuse_all_gather=true --xla_tpu_enable_async_collective_fusion_multiple_steps=true --xla_tpu_overlap_compute_collective_tc=true --xla_enable_async_all_gather=true"

echo "done; r10 records in $R/tp_tpu_r10.jsonl" >&2
exit $rc9
