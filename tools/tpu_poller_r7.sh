#!/usr/bin/env bash
# Round-7 tunnel poller: probe the axon relay port every 60s; when it
# answers twice in a row (10s apart), run the round-7 suite once and exit.
# The r7 suite records the r6 headline e2e pair FIRST, then the scan-over-
# layers compile + deep step-time pairs, then chains the r4/r5 backlogs.
# Gives up after ~11 h.
set -u
cd "$(dirname "$0")/.."
probe() { timeout 2 bash -c '</dev/tcp/127.0.0.1/8082' 2>/dev/null; }
deadline=$(( $(date +%s) + 39600 ))
while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    sleep 10
    if probe; then
      echo "tunnel up at $(date -u +%FT%TZ); running r7 followup suite" >&2
      bash tools/tpu_followup_r7.sh
      exit $?
    fi
  fi
  sleep 60
done
echo "poller gave up: tunnel never answered" >&2
exit 3
