#!/usr/bin/env python
"""Restack a checkpoint between the unrolled and scanned layer layouts.

``--scan_layers`` (models/transformer.py) stores every transformer block
weight stacked on a leading ``(num_layers, ...)`` dim under one ``layers``
subtree; the unrolled layout keeps ``num_layers`` separate ``layer_{i}``
subtrees. A checkpoint written in one layout cannot restore into the other
— ``train/engine.py`` refuses the mismatch with a pointer here. This tool
converts a whole step directory (params AND their optimizer-state mirrors,
which carry the same per-layer subtrees) and writes a new checkpoint tree
the other mode restores from directly:

    # unrolled run -> continue under --scan_layers
    python tools/convert_checkpoint.py --src outputs --dst outputs_scan \
        --to scanned
    python ddp.py --model gpt-small --scan_layers --output_dir outputs_scan

    # scanned run -> back to the unrolled layout
    python tools/convert_checkpoint.py --src outputs_scan --dst outputs \
        --to unrolled

Conversion is lossless and involutive (tests/test_scan_layers.py pins the
round-trip bit-exact). The RNG-stream provenance note: the converted
checkpoint records the *current* host's native-RNG availability, so an
exact mid-epoch data-order replay additionally needs the same RNG stream
as the original run (checkpoint/manager.py warns on restore if not).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def convert_state(state, to: str, pipe_stages: int | None = None):
    """Return ``state`` restacked into layout ``to`` ("scanned" /
    "unrolled" / "pipelined"), failing with intent when the tree is
    already there (or has no layer stack at all — e.g. an MLP/ResNet
    checkpoint).

    The pipelined entries (models/gpt_pipe.py) stack their block
    weights ``(n_stages, layers_per_stage, ...)`` under one ``blocks``
    subtree. ``--to pipelined --pipe_stages N`` restacks onto N stages
    (the resharding move: resume the same run on a different pipe
    degree); ``--to scanned``/``--to unrolled`` on a pipelined
    checkpoint convert its blocks to the r7 layer layouts (the
    interchange forms) — all conversions are lossless reshapes,
    round-tripping bit-exact (tests/test_pipeline.py).

    Since r18 the converter core lives in
    ``parallel/stacking.convert_tree_layout`` so the SAME logic runs
    inside ``CheckpointManager``'s reshard-on-restore path; this CLI
    keeps the strict contract (a no-op conversion is refused).
    """
    from pytorch_ddp_template_tpu.parallel.stacking import (
        convert_tree_layout,
    )

    return convert_tree_layout(state, to, pipe_stages=pipe_stages,
                               strict=True)


def convert_checkpoint(src: str, dst: str, to: str,
                       step: int | None = None,
                       pipe_stages: int | None = None) -> int:
    """Convert one step of ``src`` into a fresh checkpoint tree at ``dst``;
    returns the converted step number."""
    import json

    from pytorch_ddp_template_tpu.checkpoint.manager import CheckpointManager
    from pytorch_ddp_template_tpu.config import TrainingConfig

    if Path(dst).resolve() == Path(src).resolve():
        raise ValueError(
            "--dst must differ from --src: orbax owns the step layout under "
            "a managed directory, and converting in place would race the "
            "source it reads from"
        )
    src_mngr = CheckpointManager(src)
    try:
        step, state, cfg = src_mngr.restore_raw(step)
    finally:
        src_mngr.close()
    converted = convert_state(state, to, pipe_stages=pipe_stages)
    cfg = dict(cfg or {})
    if to != "pipelined":
        cfg["scan_layers"] = to == "scanned"
    # provenance keys (_native_rng, _train_batch_size) are recomputed by
    # save() from the reconstructed config — no manual carry-over needed
    config = TrainingConfig.from_json(json.dumps(cfg))
    dst_mngr = CheckpointManager(dst)
    try:
        dst_mngr.save(step, converted, config, force=True)
        dst_mngr.wait()
    finally:
        dst_mngr.close()
    return step


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--src", required=True,
                   help="checkpoint directory to read (an --output_dir)")
    p.add_argument("--dst", required=True,
                   help="directory for the converted checkpoint (must "
                        "differ from --src)")
    p.add_argument("--to", required=True,
                   choices=["scanned", "unrolled", "pipelined"],
                   help="destination layer layout (pipelined = the "
                        "gpt-pipe (n_stages, layers_per_stage, ...) "
                        "stage stacking; needs --pipe_stages)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step to convert (default: latest)")
    p.add_argument("--pipe_stages", type=int, default=None,
                   help="target pipeline stage count for --to pipelined "
                        "(must divide the layer count)")
    args = p.parse_args(argv)
    step = convert_checkpoint(args.src, args.dst, args.to, args.step,
                              pipe_stages=args.pipe_stages)
    print(f"converted step {step}: {args.src} -> {args.dst} ({args.to})")


if __name__ == "__main__":
    main()
