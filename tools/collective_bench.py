"""Collective correctness + bandwidth harness — the nccl-tests analogue.

The reference's entire native-comm capability is NCCL, exercised only
implicitly through DDP (SURVEY.md §2c/§5.8); the community verifies such
stacks with nccl-tests. On TPU the collectives are XLA's, emitted over
ICI/DCN, and this harness plays the same role: for each collective
(psum, all_gather, ppermute, psum_scatter — XLA's reduce_scatter —
and all_to_all) it

1. checks numerical correctness against the closed-form expectation, and
2. measures achieved algorithm bandwidth across a size sweep.

Run on any mesh: a real TPU slice, or CPU with
``--xla_force_host_platform_device_count=8`` (correctness only — CPU
"bandwidth" is memcpy). One JSON line per (collective, size).

Usage: python tools/collective_bench.py [--mesh data:-1] [--max-mb 64]
       python tools/collective_bench.py --cpu 8   # 8 virtual CPU devices
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P


def _bench(fn, x, *, warmup=2, iters=10):
    y = None
    for _ in range(warmup):
        y = fn(x)
    jax.block_until_ready(y)
    t0 = time.perf_counter()
    for _ in range(iters):
        y = fn(x)
    jax.block_until_ready(y)
    return (time.perf_counter() - t0) / iters, y


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mesh", default="data:-1")
    ap.add_argument("--axis", default="data")
    ap.add_argument("--max-mb", type=float, default=64.0)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--cpu", type=int, default=0, metavar="N",
                    help="Force the CPU backend with N virtual devices "
                         "(some plugin platforms ignore JAX_PLATFORMS env).")
    args = ap.parse_args(argv)

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)

    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from pytorch_ddp_template_tpu.runtime import make_mesh

    mesh = make_mesh(args.mesh, jax.devices())
    axis = args.axis
    n = mesh.shape[axis]
    spec = P(axis)
    sharding = NamedSharding(mesh, spec)

    sizes = []
    mb = 0.25
    while mb <= args.max_mb:
        sizes.append(int(mb * (1 << 20) // 4))  # f32 elements
        mb *= 4

    collectives = {
        # psum: the DDP gradient allreduce equivalent. bus bytes ~ 2*(n-1)/n * size
        "psum": (
            lambda x: shard_map(
                partial(lax.psum, axis_name=axis), mesh=mesh,
                in_specs=spec, out_specs=P(), check_vma=False,
            )(x),
            lambda local_sum: local_sum,  # expectation handled below
            2.0 * (n - 1) / n,
        ),
        "all_gather": (
            lambda x: shard_map(
                partial(lax.all_gather, axis_name=axis, tiled=True),
                mesh=mesh, in_specs=spec, out_specs=P(), check_vma=False,
            )(x),
            None,
            1.0 * (n - 1) / n,
        ),
        "ppermute": (
            lambda x: shard_map(
                lambda v: lax.ppermute(
                    v, axis, [(i, (i + 1) % n) for i in range(n)]
                ),
                mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False,
            )(x),
            None,
            1.0 / n,  # each chip sends its shard one hop
        ),
        # psum_scatter (reduce_scatter): the ZeRO/FSDP gradient primitive —
        # each rank ends with its reduced shard (half an allreduce's wire
        # traffic; GSPMD emits it for fsdp_reshard'd grads)
        "psum_scatter": (
            lambda x: shard_map(
                lambda v: lax.psum_scatter(v, axis, tiled=True),
                mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False,
            )(x),
            None,
            1.0 * (n - 1) / n,
        ),
        # all_to_all: the MoE dispatch primitive (parallel/expert.py).
        # Each rank splits its shard n ways and exchanges; (n-1)/n of
        # every shard crosses the wire.
        "all_to_all": (
            lambda x: shard_map(
                lambda v: lax.all_to_all(
                    v.reshape(n, -1), axis, split_axis=0, concat_axis=0
                ).reshape(-1),
                mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False,
            )(x),
            None,
            1.0 * (n - 1) / n,
        ),
    }

    ok_all = True
    for name, (fn, _, bus_factor) in collectives.items():
        for elems in sizes:
            # all_to_all and psum_scatter re-split each shard n ways; the
            # rest need only n. Never round to zero — an empty array would
            # time a no-op and count a vacuous "correct" toward the verdict.
            quantum = n * n if name in ("all_to_all", "psum_scatter") else n
            elems = max((elems // quantum) * quantum, quantum)
            host = np.arange(elems, dtype=np.float32)
            x = jax.device_put(jnp.asarray(host), sharding)
            dt, y = _bench(jax.jit(fn), x, iters=args.iters)
            y = np.asarray(y)

            if name == "psum":
                # global sum of the sharded vector, replicated: psum over
                # shards == elementwise sum of the n shards
                want = host.reshape(n, -1).sum(axis=0)
                good = np.allclose(y, want)
            elif name == "all_gather":
                good = np.array_equal(y, host)
            elif name == "psum_scatter":
                # rank r ends with the cross-rank sum of everyone's tile r
                want = host.reshape(n, n, -1).sum(axis=0).reshape(-1)
                good = np.allclose(y, want)
            elif name == "all_to_all":
                # rank r ends with chunk r of every source, source-ordered:
                # a (source, chunk) transpose of the sharded layout
                want = host.reshape(n, n, -1).transpose(1, 0, 2).reshape(-1)
                good = np.array_equal(y, want)
            else:  # ppermute: shard i receives shard i-1
                want = host.reshape(n, -1)[(np.arange(n) - 1) % n].reshape(-1)
                good = np.array_equal(y, want)
            ok_all &= good

            size_bytes = elems * 4
            print(json.dumps({
                "collective": name,
                "devices": n,
                "size_mb": round(size_bytes / (1 << 20), 3),
                "time_ms": round(dt * 1e3, 3),
                "alg_gbps": round(size_bytes / dt / 1e9, 3),
                "bus_gbps": round(bus_factor * size_bytes / dt / 1e9, 3),
                "correct": bool(good),
            }))

    print(json.dumps({"all_correct": bool(ok_all), "mesh": dict(mesh.shape)}))
    return 0 if ok_all else 1


if __name__ == "__main__":
    raise SystemExit(main())
