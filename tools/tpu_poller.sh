#!/usr/bin/env bash
# Consolidated tunnel poller (r15 satellite): one parameterized script
# replacing the per-round tpu_poller_rN.sh copies (old spellings remain
# as thin shims). Probes the axon relay port every 60s; when it answers
# twice in a row (10s apart), runs `tools/tpu_followup.sh <round>` once
# and exits with its status. The followup chains the full historical
# backlog for the round (headline e2e pair first, then r7/r4/r5, then
# r8..<round> — see tools/tpu_followup.sh). Gives up after ~11 h.
# Usage: bash tools/tpu_poller.sh <round>
set -u
ROUND=${1:?usage: tpu_poller.sh <round: 4..17>}
cd "$(dirname "$0")/.."
probe() { timeout 2 bash -c '</dev/tcp/127.0.0.1/8082' 2>/dev/null; }
deadline=$(( $(date +%s) + 39600 ))
while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    sleep 10
    if probe; then
      echo "tunnel up at $(date -u +%FT%TZ); running round-$ROUND followup suite" >&2
      bash tools/tpu_followup.sh "$ROUND"
      exit $?
    fi
  fi
  sleep 60
done
echo "poller gave up: tunnel never answered" >&2
exit 3
