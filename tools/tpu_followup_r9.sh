#!/usr/bin/env bash
# Round-9 TPU measurement suite. Ordering per the established pattern:
# (1) the r8 backlog FIRST (tools/tpu_followup_r8.sh — itself chaining the
# r7 backlog, headed by the still-open r6 e2e host-overhead headline
# pair), then (2) the round-9 compressed-DDP comms legs on the real chip.
# Note: the current tunnel exposes ONE v5e chip — at data:1 the comms
# record is marked `degenerate` (no cross-replica gradient bytes exist to
# compress or overlap) and serves as the parity + HLO-schedule probe
# against the real TPU compiler; the step-time and wire-bytes WIN cases
# need a multi-chip slice and stay flagged for the next topology change
# (per the r8 convention). The latency-hiding-scheduler pack pairs with
# --ddp_overlap the same way it pairs with --fsdp_overlap.
# Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r9.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, outfile, env... — logs one JSON line or the error
  local name=$1 out=$2; shift 2
  echo "=== $name ===" >&2
  env "$@" timeout 1200 python bench.py 2>>"$R/.followup_r9.err" | tee -a "$R/$out"
}

# 1. the r8 backlog first (r7 chain -> r8 overlap legs)
bash tools/tpu_followup_r8.sh
rc8=$?

# 2. round-9 comms legs
#    (a) BENCH_MODE=comms on the chip: fp32 bit-parity + per-layer
#        in-scan HLO reduce evidence + wire-byte table + the EF
#        convergence triple against the real TPU compiler (step-time
#        ratio degenerate at data:1; still the first real-Mosaic record)
run comms_legs comms_tpu_r9.jsonl BENCH_MODE=comms
#    (b) the latency-hiding-scheduler pack A/B over the compressed-DDP
#        train step: gpt-small --scan_layers --ddp_overlap with and
#        without the pack — whether the scheduler actually drains the
#        per-layer reduces under backward compute on real hardware
run ddp_lhs_off comms_tpu_r9.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_DDP_OVERLAP=1
run ddp_lhs_on  comms_tpu_r9.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_DDP_OVERLAP=1 \
    XLA_FLAGS="--xla_tpu_enable_latency_hiding_scheduler=true --xla_tpu_enable_async_collective_fusion=true --xla_tpu_enable_async_collective_fusion_fuse_all_gather=true --xla_tpu_enable_async_collective_fusion_multiple_steps=true --xla_tpu_overlap_compute_collective_tc=true --xla_enable_async_all_gather=true"

echo "done; r9 records in $R/comms_tpu_r9.jsonl" >&2
exit $rc8
