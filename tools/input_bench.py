"""Host input-path microbenchmark: synthetic vs file-backed gather.

SURVEY.md §7 hard part (e): on TPU the input pipeline (host CPU), not the
model math, is the classic bottleneck — this tool measures the host-side
examples/sec of each source so input-boundness can be diagnosed without
touching a chip (compare against the step time ``StepTimer`` reports).

Usage::

    python tools/input_bench.py --model resnet18 --batch 256 --iters 20
"""

from __future__ import annotations

import argparse
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _time_batches(dataset, batch: int, iters: int, seed: int = 0) -> float:
    rng = np.random.default_rng(seed)
    n = len(dataset)
    idx = [rng.integers(0, n, batch) for _ in range(iters)]
    dataset.batch(idx[0])  # warm page cache / native threads
    t0 = time.perf_counter()
    for i in idx:
        dataset.batch(i)
    return iters * batch / (time.perf_counter() - t0)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="resnet18")
    p.add_argument("--samples", type=int, default=4096)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--store", default=None,
                   help="existing store dir; default materialises a "
                        "temporary one from the synthetic source")
    args = p.parse_args(argv)

    from pytorch_ddp_template_tpu import native
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.data.filestore import (
        MemmapDataset,
        materialize,
    )
    from pytorch_ddp_template_tpu.models import build

    config = TrainingConfig(model=args.model, dataset_size=args.samples)
    _, synth = build(args.model, config)
    results = {
        "native": native.available(),
        "synthetic_ex_per_s": _time_batches(synth, args.batch, args.iters),
    }

    tmp = None
    if args.store:
        store_dir = args.store
    else:
        tmp = tempfile.TemporaryDirectory(prefix="input_bench_")
        store_dir = tmp.name + "/store"
        materialize(synth, store_dir, samples=args.samples)
    filed = MemmapDataset(store_dir)
    results["file_ex_per_s"] = _time_batches(filed, args.batch, args.iters)
    results["file_vs_synth"] = round(
        results["file_ex_per_s"] / results["synthetic_ex_per_s"], 3
    )
    for k, v in results.items():
        if k == "file_vs_synth":
            print(f"{k}: {v:.3f}")
        else:
            print(f"{k}: {v:.1f}" if isinstance(v, float) else f"{k}: {v}")
    if tmp is not None:
        tmp.cleanup()
    return 0


if __name__ == "__main__":
    sys.exit(main())
