#!/usr/bin/env bash
# Round-11 TPU measurement suite. Ordering per the established pattern:
# (1) the r10 backlog FIRST (tools/tpu_followup_r10.sh — itself chaining
# r9/r8/r7, headed by the still-open r6 e2e host-overhead headline pair
# and the r10 TP legs that need a multi-chip slice), then (2) the
# round-11 composed-schedule legs on the real chip.
# Note: the current tunnel exposes ONE v5e chip — BENCH_MODE=overlap3d
# needs a data:N>=2 × model:M>=2 mesh, so a single-chip run emits a
# `degenerate` zero-value record (nothing to compose; the r8
# convention). The real legs — composed fsdp-gathers-under-ring-dots
# parity on the Mosaic compiler, the step-time ratio with BOTH axes'
# collectives hidden by real ICI latency, and the latency-hiding pack
# A/B over the composed step — stay flagged for the next multi-chip
# tunnel window.
# Safe to re-run; each mode appends one JSON line.
# Usage: bash tools/tpu_followup_r11.sh   (requires the axon tunnel up)
set -u
cd "$(dirname "$0")/.."
R=bench_records
mkdir -p "$R"

run() { # name, outfile, env... — logs one JSON line or the error
  local name=$1 out=$2; shift 2
  echo "=== $name ===" >&2
  env "$@" timeout 1200 python bench.py 2>>"$R/.followup_r11.err" | tee -a "$R/$out"
}

# 1. the r10 backlog first (r9/r8/r7 chain -> r10 TP legs)
bash tools/tpu_followup_r10.sh
rc10=$?

# 2. round-11 composed-schedule legs
#    (a) BENCH_MODE=overlap3d on the chip: degenerate marker at 1 chip;
#        on a multi-chip slice this is the real record — composed
#        fsdp×tp parity vs the FLOPs-matched (remat) GSPMD default, the
#        both-axes HLO schedule evidence from the Mosaic compiler, and
#        the step-time ratio with real ICI latency under the dots
run overlap3d_legs overlap3d_tpu_r11.jsonl BENCH_MODE=overlap3d
#    (b) the latency-hiding-scheduler pack A/B over the composed
#        fsdp×tp train step (multi-chip only — gpt-small heads/mlp
#        divide model:2): whether the scheduler runs the data-axis
#        gathers AND the single-hop ppermutes under the partial dots at
#        the same time on real hardware. Harmless degenerate-config
#        failure at 1 chip (refused with intent at mesh validation).
run o3d_lhs_off overlap3d_tpu_r11.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_TP_OVERLAP=1 BENCH_FSDP_OVERLAP=1
run o3d_lhs_on  overlap3d_tpu_r11.jsonl BENCH_MODE=train BENCH_MODEL=gpt-small BENCH_BATCH=4 BENCH_SCAN=1 BENCH_TP_OVERLAP=1 BENCH_FSDP_OVERLAP=1 \
    XLA_FLAGS="--xla_tpu_enable_latency_hiding_scheduler=true --xla_tpu_enable_async_collective_fusion=true --xla_tpu_enable_async_collective_fusion_fuse_all_gather=true --xla_tpu_enable_async_collective_fusion_multiple_steps=true --xla_tpu_overlap_compute_collective_tc=true --xla_enable_async_all_gather=true"

echo "done; r11 records in $R/overlap3d_tpu_r11.jsonl" >&2
exit $rc10
