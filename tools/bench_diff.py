#!/usr/bin/env python
"""Diff two bench-record sets; exit non-zero on out-of-band drift.

The committed ``bench_records/*.jsonl`` files document every round's
evidence — but documentation does not fail CI. This tool turns them into
executable perf-regression tripwires (the r14 fleet-watchtower
convention, the CLI sibling of ``obs/regression.py``;
``tools/ci_bench_check.sh`` is the one-command CI wrapper):

    # a fresh record vs the committed one (the BENCH_MODE=fleet leg)
    python tools/bench_diff.py bench_records/perf_cpu_r13.jsonl /tmp/new.jsonl

    # whole directories: every metric present in both sides is compared
    python tools/bench_diff.py bench_records /tmp/fresh_records

    # markdown for a PR comment / CI summary
    python tools/bench_diff.py old.jsonl new.jsonl --format github

Each side may be a ``.jsonl`` file or a directory of them. Records
group by ``metric``; each side's best (max-value) non-ablation record
represents the metric (the ``_last_recorded`` convention: a
deliberately degraded config must not define the bar). Every bench
metric in this repo is higher-is-better (throughputs, speedups, and
the ≥0.9 neutrality-band ratios), so drift means
``new < base * (1 - tolerance)``. Improvements report as OK.

Exit codes: 0 in-band, 1 drift, 2 usage/no-overlap (an empty comparison
must not read as a green tripwire).

Stdlib-only on purpose: runs anywhere, including hosts with no jax.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

#: record keys that mark an ablation run (mirrors bench.py; duplicated
#: rather than imported so this tool stays importable without the repo
#: root on sys.path — the two lists are pinned equal by a test)
ABLATION_KEYS = ("remat", "fused_head", "dense_head", "flash_disabled",
                 "num_layers", "scan_layers", "ddp_overlap", "tp_overlap",
                 "fsdp_overlap", "quant_compute", "kv_quant", "paged_impl",
                 "spec_k", "draft_depth", "tp_degree", "pipe_schedule")


def _paths(target: str) -> list[str]:
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "*.jsonl")))
    return [target]


def load_records(target: str) -> dict[str, dict]:
    """``metric -> best record`` over a file or directory of JSONL
    records. Error rows (``value`` 0/absent) and ablation rows are
    skipped — the bar is the best honest number."""
    best: dict[str, dict] = {}
    for path in _paths(target):
        try:
            lines = open(path).read().splitlines()
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
            except (ValueError, TypeError):
                continue
            metric = rec.get("metric")
            value = rec.get("value")
            if not metric or not isinstance(value, (int, float)) or value <= 0:
                continue
            if any(rec.get(k) for k in ABLATION_KEYS):
                continue
            rec = dict(rec)
            rec["_source"] = os.path.basename(path)
            if metric not in best or value > best[metric]["value"]:
                best[metric] = rec
    return best


def diff_records(base: dict[str, dict], new: dict[str, dict], *,
                 tolerance: float) -> list[dict]:
    """One row per metric present in BOTH sides, ratio = new/base,
    ``drift`` when the new value fell out of band."""
    rows = []
    for metric in sorted(set(base) & set(new)):
        b, n = base[metric]["value"], new[metric]["value"]
        ratio = n / b if b else 0.0
        rows.append({
            "metric": metric,
            "unit": new[metric].get("unit") or base[metric].get("unit"),
            "base": b,
            "new": n,
            "ratio": round(ratio, 4),
            "drift": ratio < 1.0 - tolerance,
            "base_source": base[metric].get("_source"),
            "new_source": new[metric].get("_source"),
        })
    return rows


def render(rows: list[dict], fmt: str, *, tolerance: float) -> str:
    """``text`` (aligned columns) or ``github`` (markdown table)."""
    status = lambda r: "DRIFT" if r["drift"] else "ok"  # noqa: E731
    if fmt == "github":
        out = [
            f"### bench_diff (band: new ≥ {1 - tolerance:.2f}× base)",
            "",
            "| metric | base | new | ratio | status |",
            "|---|---:|---:|---:|---|",
        ]
        for r in rows:
            mark = "❌ DRIFT" if r["drift"] else "✅ ok"
            out.append(f"| `{r['metric']}` | {r['base']:g} | {r['new']:g} "
                       f"| {r['ratio']:.3f} | {mark} |")
        return "\n".join(out)
    width = max([len(r["metric"]) for r in rows] + [6])
    out = [f"{'metric':<{width}}  {'base':>12}  {'new':>12}  "
           f"{'ratio':>7}  status"]
    for r in rows:
        out.append(f"{r['metric']:<{width}}  {r['base']:>12g}  "
                   f"{r['new']:>12g}  {r['ratio']:>7.3f}  {status(r)}")
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("base", help="baseline .jsonl file or directory "
                                "(e.g. bench_records)")
    p.add_argument("new", help="candidate .jsonl file or directory")
    p.add_argument("--tolerance", type=float, default=0.25,
                   help="allowed relative drop before a metric counts as "
                        "drift (0.25 = the new value may be up to 25%% "
                        "below the base; generous by default — CPU bench "
                        "numbers carry ambient noise)")
    p.add_argument("--format", choices=["text", "github"], default="text",
                   help="'github' emits a markdown table for PR/CI "
                        "summaries")
    args = p.parse_args(argv)
    if not (0.0 < args.tolerance < 1.0):
        print(f"--tolerance must be in (0, 1), got {args.tolerance}",
              file=sys.stderr)
        return 2

    base = load_records(args.base)
    new = load_records(args.new)
    rows = diff_records(base, new, tolerance=args.tolerance)
    if not rows:
        # zero overlap is NOT a pass: a renamed metric or an empty file
        # would otherwise silently disarm the tripwire
        print(f"no common metrics between {args.base!r} ({len(base)} "
              f"metrics) and {args.new!r} ({len(new)} metrics)",
              file=sys.stderr)
        return 2
    print(render(rows, args.format, tolerance=args.tolerance))
    drifted = [r["metric"] for r in rows if r["drift"]]
    if drifted:
        print(f"DRIFT: {len(drifted)}/{len(rows)} metrics out of band "
              f"(> {100 * args.tolerance:g}% below base): "
              + ", ".join(drifted), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
