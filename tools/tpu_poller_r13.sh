#!/usr/bin/env bash
# Round-13 tunnel poller: probe the axon relay port every 60s; when it
# answers twice in a row (10s apart), run the round-13 suite once and
# exit. The r13 suite chains the r12 backlog FIRST (which itself leads
# with the r11/r10/r9/r8/r7 chains and the r6 e2e headline pair), then
# records the performance-attribution legs — the BENCH_MODE=perf
# neutrality pair with a REAL v5e MFU (no calibration: the PEAK_FLOPS
# table applies), the mfu_probe cross-check, and a --perf_report +
# --profile_steps run whose trace carries the named loop/schedule
# phases. Gives up after ~11 h.
set -u
cd "$(dirname "$0")/.."
probe() { timeout 2 bash -c '</dev/tcp/127.0.0.1/8082' 2>/dev/null; }
deadline=$(( $(date +%s) + 39600 ))
while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    sleep 10
    if probe; then
      echo "tunnel up at $(date -u +%FT%TZ); running r13 followup suite" >&2
      bash tools/tpu_followup_r13.sh
      exit $?
    fi
  fi
  sleep 60
done
echo "poller gave up: tunnel never answered" >&2
exit 3
